"""Native checkpoint + preemption + state auditor (utils/checkpoint.py,
utils/audit.py, World run hardening).

Fast tier: the pure-host generation store (atomic manifest + CRC32,
fault injection by byte flip / truncation, fallback ordering, rolling
retention) and the .spop symbol-encoding satellite -- no jit involved.

Slow tier: end-to-end bit-exact resume through the SIGTERM preemption
path (XLA engine, systematics on) and through the Pallas kernel path
with budget-aware lane packing; corrupt-checkpoint fallback on a real
world; the invariant auditor on evolved state with injected NaN merit
and a clobbered lane permutation.
"""

from __future__ import annotations

import json
import os
import signal

import numpy as np
import pytest

from avida_tpu.utils import checkpoint as ckpt_mod


# ---------------------------------------------------------------------------
# fast: generation store fault injection (no jax compilation)
# ---------------------------------------------------------------------------

def _arrays():
    rng = np.random.default_rng(3)
    return {
        "state.a": np.arange(37, dtype=np.int32),
        "state.b": rng.random((5, 9)).astype(np.float32),
        "state.c": rng.integers(0, 2, 64).astype(bool),
    }


def test_generation_write_verify_roundtrip(tmp_path):
    base = str(tmp_path / "ck")
    arrays = _arrays()
    host = {"update": 12, "avida_time": 1.5, "gen_next": [None, 3.0]}
    path = ckpt_mod.write_generation(base, 12, arrays, host, keep=2)
    assert os.path.basename(path) == "ckpt-000000000012"
    manifest, back, files = ckpt_mod.read_generation(path)
    assert manifest["update"] == 12
    assert manifest["host"] == json.loads(json.dumps(host))
    for name, arr in arrays.items():
        np.testing.assert_array_equal(back[name], arr)
        assert back[name].dtype == arr.dtype
    # no stray tmp dirs survive a successful publish
    assert not [d for d in os.listdir(base) if d.startswith(".tmp-")]


def test_byte_flip_detected(tmp_path):
    base = str(tmp_path / "ck")
    path = ckpt_mod.write_generation(base, 1, _arrays(), {}, keep=2)
    target = os.path.join(path, "state.b.npy")
    blob = bytearray(open(target, "rb").read())
    blob[len(blob) // 2] ^= 0x01          # single-bit flip in the payload
    open(target, "wb").write(bytes(blob))
    with pytest.raises(ckpt_mod.CheckpointError, match="CRC mismatch"):
        ckpt_mod.verify_generation(path)


def test_truncation_detected(tmp_path):
    base = str(tmp_path / "ck")
    path = ckpt_mod.write_generation(base, 1, _arrays(), {}, keep=2)
    target = os.path.join(path, "state.a.npy")
    blob = open(target, "rb").read()
    open(target, "wb").write(blob[: len(blob) // 2])
    with pytest.raises(ckpt_mod.CheckpointError, match="CRC mismatch"):
        ckpt_mod.verify_generation(path)
    # a missing array file is caught too
    os.remove(target)
    with pytest.raises(ckpt_mod.CheckpointError, match="missing"):
        ckpt_mod.verify_generation(path)


def test_truncated_manifest_detected(tmp_path):
    base = str(tmp_path / "ck")
    path = ckpt_mod.write_generation(base, 1, _arrays(), {}, keep=2)
    mpath = os.path.join(path, ckpt_mod.MANIFEST)
    blob = open(mpath, "rb").read()
    open(mpath, "wb").write(blob[: len(blob) // 3])
    with pytest.raises(ckpt_mod.CheckpointError, match="manifest"):
        ckpt_mod.verify_generation(path)


def test_fallback_to_previous_generation(tmp_path):
    base = str(tmp_path / "ck")
    ckpt_mod.write_generation(base, 10, _arrays(), {"u": 10}, keep=3)
    newest = ckpt_mod.write_generation(base, 20, _arrays(), {"u": 20}, keep=3)
    target = os.path.join(newest, "state.c.npy")
    blob = bytearray(open(target, "rb").read())
    blob[-1] ^= 0xFF
    open(target, "wb").write(bytes(blob))

    skipped = []
    path, manifest = ckpt_mod.latest_valid(
        base, on_skip=lambda p, e: skipped.append(p))
    assert manifest["update"] == 10
    assert skipped == [newest]


def test_rolling_retention(tmp_path):
    base = str(tmp_path / "ck")
    for u in (1, 2, 3, 4):
        ckpt_mod.write_generation(base, u, _arrays(), {}, keep=2)
    names = sorted(os.path.basename(p)
                   for p in ckpt_mod.list_generations(base))
    assert names == ["ckpt-000000000003", "ckpt-000000000004"]


def test_stale_tmp_swept(tmp_path):
    base = str(tmp_path / "ck")
    os.makedirs(os.path.join(base, ".tmp-ckpt-000000000099.1234"))
    ckpt_mod.write_generation(base, 5, _arrays(), {}, keep=2)
    assert not [d for d in os.listdir(base) if d.startswith(".tmp-")]


def test_resume_falls_back_past_torn_manifest(tmp_path):
    """A manifest.json truncated MID-WRITE (the fault framework's
    deterministic torn-manifest mode, utils/faultinject.py) raises the
    distinct CheckpointManifestError and the restore scan falls back to
    the previous generation -- resume survives a torn save, not just
    bad-CRC leaves."""
    from avida_tpu.utils import faultinject as fi
    base = str(tmp_path / "ck")
    good = ckpt_mod.write_generation(base, 10, _arrays(), {"u": 10}, keep=3)
    newest = ckpt_mod.write_generation(base, 20, _arrays(), {"u": 20}, keep=3)
    fi.tear_manifest(newest, fi.parse_spec("torn-manifest", seed=4)[0].rng)
    with pytest.raises(ckpt_mod.CheckpointManifestError, match="manifest"):
        ckpt_mod.verify_generation(newest)

    skipped = []
    path, manifest = ckpt_mod.latest_valid(
        base, on_skip=lambda p, e: skipped.append((p, e)))
    assert path == good and manifest["update"] == 10
    assert [p for p, _ in skipped] == [newest]
    assert isinstance(skipped[0][1], ckpt_mod.CheckpointManifestError)


def test_resume_falls_back_past_empty_manifest(tmp_path):
    """Truncation edge: the crash landed before ANY manifest byte was
    flushed (0-byte file).  Still a torn manifest, still skipped."""
    base = str(tmp_path / "ck")
    good = ckpt_mod.write_generation(base, 10, _arrays(), {}, keep=3)
    newest = ckpt_mod.write_generation(base, 20, _arrays(), {}, keep=3)
    os.truncate(os.path.join(newest, ckpt_mod.MANIFEST), 0)
    with pytest.raises(ckpt_mod.CheckpointManifestError):
        ckpt_mod.verify_generation(newest)
    path, manifest = ckpt_mod.latest_valid(base)
    assert path == good and manifest["update"] == 10


# ---------------------------------------------------------------------------
# fast: .spop sequence symbol encoding satellite (a-z then A-Z, cap 52)
# ---------------------------------------------------------------------------

def test_spop_symbol_encoding_roundtrip():
    from avida_tpu.utils.spop import _seq_to_string, _string_to_seq
    ops = np.arange(52, dtype=np.int8)
    s = _seq_to_string(ops)
    assert s == ("abcdefghijklmnopqrstuvwxyz"
                 "ABCDEFGHIJKLMNOPQRSTUVWXYZ")
    np.testing.assert_array_equal(_string_to_seq(s), ops)
    with pytest.raises(ValueError, match="52"):
        _seq_to_string(np.asarray([52], np.int32))
    with pytest.raises(ValueError, match="symbol"):
        _string_to_seq("ab{c")


# ---------------------------------------------------------------------------
# slow: end-to-end world tests
# ---------------------------------------------------------------------------

_NB_SCRATCH = ("nb_genome", "nb_len", "nb_cell", "nb_parent", "nb_update")


def _assert_states_equal(sa, sb):
    """Bit-exact PopulationState comparison.  The newborn ring-buffer
    record rows are compared only up to nb_count (zero after the run-end
    drain): rows past the cursor are dead scratch whose stale contents
    depend on drain/chunk boundaries, which resume legitimately
    re-chunks -- every live field must match exactly."""
    for name in sa.__dataclass_fields__:
        va, vb = np.asarray(getattr(sa, name)), np.asarray(getattr(sb, name))
        if name in _NB_SCRATCH:
            cnt = int(np.asarray(sa.nb_count))
            va, vb = va[:cnt], vb[:cnt]
        np.testing.assert_array_equal(va, vb, err_msg=f"field {name}")


def _xla_world(tmpdir, ckpt=None, every=0, seed=11):
    from avida_tpu.config import AvidaConfig
    from avida_tpu.world import World
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.TPU_MAX_MEMORY = 256
    cfg.RANDOM_SEED = seed
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    if ckpt:
        cfg.set("TPU_CKPT_DIR", str(ckpt))
    if every:
        cfg.set("TPU_CKPT_EVERY", every)
    w = World(cfg=cfg, data_dir=str(tmpdir))
    w.events = []
    return w


@pytest.mark.slow
def test_sigterm_preempt_resume_bit_exact(tmp_path):
    """Run N updates uninterrupted; separately, SIGTERM the run at ~N/2
    (the preemption path: flag at the chunk boundary, drain, final
    checkpoint, clean return), resume a FRESH world from the checkpoint
    and continue to N.  Final PopulationState, host counters and the
    systematics tables must match the uninterrupted run's exactly."""
    from avida_tpu.config.events import parse_event_line
    from avida_tpu.core.state import state_field_names

    wa = _xla_world(tmp_path / "a")
    wa.inject()
    wa.run(max_updates=20)

    ckdir = tmp_path / "ck"
    wb = _xla_world(tmp_path / "b", ckpt=ckdir)
    wb._action_SendTerm = \
        lambda args: os.kill(os.getpid(), signal.SIGTERM)
    wb.events = [parse_event_line("u 9 SendTerm")]
    wb.inject()
    wb.run(max_updates=20)
    assert wb.preempted
    assert wb.update < 20
    gens = ckpt_mod.list_generations(str(ckdir))
    assert len(gens) == 1

    # the manifest covers EVERY materialized PopulationState field
    # (format versioning: adding a field must change the manifest field
    # set; None-valued fields -- the flight-recorder ring with TPU_TRACE
    # off -- are empty pytrees with no on-disk representation), with the
    # live state's exact shapes and dtypes
    from avida_tpu.core.state import state_array_specs
    manifest = ckpt_mod.verify_generation(gens[0])
    saved = {k for k in manifest["arrays"] if k.startswith("state.")}
    assert saved == {f"state.{f}" for f in state_array_specs(wb.state)}
    assert saved <= {f"state.{f}" for f in state_field_names()}
    for field, (shape, dtype) in state_array_specs(wb.state).items():
        spec = manifest["arrays"][f"state.{field}"]
        assert tuple(spec["shape"]) == shape, field
        assert spec["dtype"] == dtype, field

    wc = _xla_world(tmp_path / "c", ckpt=ckdir)
    assert wc.resume() == wb.update
    wc.run(max_updates=20)
    assert not wc.preempted
    _assert_states_equal(wa.state, wc.state)
    assert int(np.asarray(wa._total_births)) == int(np.asarray(wc._total_births))
    assert wa.systematics.num_genotypes == wc.systematics.num_genotypes
    assert sorted(g.sequence.tobytes()
                  for g in wa.systematics.live_genotypes()) \
        == sorted(g.sequence.tobytes()
                  for g in wc.systematics.live_genotypes())


@pytest.mark.slow
def test_auto_save_and_corrupt_fallback(tmp_path, capsys):
    """TPU_CKPT_EVERY auto-saves rolling generations; byte-flipping the
    newest makes resume fall back to the previous retained one with a
    runlog warning."""
    ckdir = tmp_path / "ck"
    w = _xla_world(tmp_path / "a", ckpt=ckdir, every=6)
    w.inject()
    w.run(max_updates=20)
    gens = ckpt_mod.list_generations(str(ckdir))
    assert len(gens) == 2          # TPU_CKPT_KEEP default
    updates = [ckpt_mod.verify_generation(g)["update"] for g in gens]
    assert updates == sorted(updates)

    target = os.path.join(gens[-1], "state.merit.npy")
    blob = bytearray(open(target, "rb").read())
    blob[len(blob) // 2] ^= 0x10
    open(target, "wb").write(bytes(blob))

    w2 = _xla_world(tmp_path / "b", ckpt=ckdir)
    assert w2.resume() == updates[0]
    err = capsys.readouterr().err
    assert "checkpoint_corrupt" in err
    assert "checkpoint_restored" in err
    # and the fallback world keeps evolving
    w2.run(max_updates=updates[0] + 4)
    assert w2.update == updates[0] + 4


@pytest.mark.slow
def test_pallas_lane_packed_resume_bit_exact(tmp_path):
    """Bit-exact resume through the Pallas kernel path with budget-aware
    lane packing active (lane_perm refreshed every update): save at
    mid-run via World.save_checkpoint, resume a fresh world, finish, and
    match the uninterrupted kernel run exactly -- including
    lane_perm/lane_inv."""
    from avida_tpu.config import AvidaConfig
    from avida_tpu.ops.update import use_pallas_path
    from avida_tpu.world import World

    def mk(tmpdir, ckpt=None):
        cfg = AvidaConfig()
        cfg.WORLD_X = 8
        cfg.WORLD_Y = 8
        cfg.TPU_MAX_MEMORY = 200
        cfg.RANDOM_SEED = 11
        cfg.COPY_MUT_PROB = 0.0
        cfg.DIVIDE_INS_PROB = 0.0
        cfg.DIVIDE_DEL_PROB = 0.0
        cfg.SLICING_METHOD = 0
        cfg.AVE_TIME_SLICE = 100
        cfg.TPU_MAX_STEPS_PER_UPDATE = 100
        cfg.TPU_USE_PALLAS = 1        # interpret mode on CPU
        cfg.set("TPU_SYSTEMATICS", 0)
        # this test targets the BUDGET-SORT lane-packed path; packed
        # residency (round 6) supersedes the permutation when active, so
        # pin it off (the packed path has its own resume test below)
        cfg.set("TPU_PACKED_CHUNK", 0)
        if ckpt:
            cfg.set("TPU_CKPT_DIR", str(ckpt))
        w = World(cfg=cfg, data_dir=str(tmpdir))
        w.events = []
        return w

    wa = mk(tmp_path / "a")
    assert use_pallas_path(wa.params) and wa.params.lane_perm_k == 1
    wa.inject()
    wa.run(max_updates=8)
    assert not np.array_equal(np.asarray(wa.state.lane_perm),
                              np.arange(wa.params.num_cells))

    ckdir = tmp_path / "ck"
    wb = mk(tmp_path / "b", ckpt=ckdir)
    wb.inject()
    wb.run(max_updates=4)
    wb.save_checkpoint()

    wc = mk(tmp_path / "c", ckpt=ckdir)
    assert wc.resume() == 4
    wc.run(max_updates=8)
    _assert_states_equal(wa.state, wc.state)


@pytest.mark.slow
def test_packed_chunk_sigterm_preempt_resume_bit_exact(tmp_path):
    """SIGTERM preemption UNDER PACKED RESIDENCY (ops/packed_chunk.py,
    mutations ON so the packed-native flush's divide-mutation path is in
    the loop): the preempt flag is honored at the chunk boundary,
    strictly AFTER update_scan's unpack, so the final checkpoint
    serializes canonical [N, L] state mid-run; a fresh world resumes
    bit-exactly and matches the uninterrupted packed run."""
    from avida_tpu.config import AvidaConfig
    from avida_tpu.config.events import parse_event_line
    from avida_tpu.ops import packed_chunk
    from avida_tpu.world import World

    def mk(tmpdir, ckpt=None):
        cfg = AvidaConfig()
        cfg.WORLD_X = 8
        cfg.WORLD_Y = 8
        cfg.TPU_MAX_MEMORY = 200
        cfg.RANDOM_SEED = 11
        cfg.AVE_TIME_SLICE = 100
        cfg.TPU_MAX_STEPS_PER_UPDATE = 100
        cfg.TPU_USE_PALLAS = 1        # interpret mode on CPU
        cfg.set("TPU_SYSTEMATICS", 0)
        if ckpt:
            cfg.set("TPU_CKPT_DIR", str(ckpt))
        w = World(cfg=cfg, data_dir=str(tmpdir))
        w.events = []
        return w

    wa = mk(tmp_path / "a")
    wa.inject()
    assert packed_chunk.active(wa.params, wa.state)
    wa.run(max_updates=12)

    ckdir = tmp_path / "ck"
    wb = mk(tmp_path / "b", ckpt=ckdir)
    wb._action_SendTerm = lambda args: os.kill(os.getpid(), signal.SIGTERM)
    wb.events = [parse_event_line("u 5 SendTerm")]
    wb.inject()
    wb.run(max_updates=12)
    assert wb.preempted and wb.update < 12
    # the checkpointed state is canonical [N, L]: the flag-bit tape and
    # the genome plane round-tripped OUT of packed residency at the
    # boundary before the save
    gens = ckpt_mod.list_generations(str(ckdir))
    manifest = ckpt_mod.verify_generation(gens[-1])
    assert tuple(manifest["arrays"]["state.tape"]["shape"]) == (64, 200)
    assert tuple(manifest["arrays"]["state.genome"]["shape"]) == (64, 200)

    wc = mk(tmp_path / "c", ckpt=ckdir)
    assert wc.resume() == wb.update
    wc.run(max_updates=12)
    _assert_states_equal(wa.state, wc.state)


@pytest.mark.slow
def test_auditor_on_evolved_state(tmp_path):
    """audit_state passes on healthy evolved state and names the exact
    invariant for injected corruption: NaN merit, a clobbered lane
    permutation, a negative resource pool."""
    import jax.numpy as jnp

    from avida_tpu.utils.audit import (StateInvariantError, audit_state,
                                       check_invariants)

    w = _xla_world(tmp_path)
    w.inject()
    w.run(max_updates=12)
    st = w.state
    counts = check_invariants(w.params, st)
    assert counts and all(v == 0 for v in counts.values())
    assert len(counts) >= 15

    cell = int(np.nonzero(np.asarray(st.alive))[0][0])
    with pytest.raises(StateInvariantError, match="merit_finite") as ei:
        check_invariants(w.params, st.replace(
            merit=st.merit.at[cell].set(jnp.nan)))
    assert ei.value.violations == {"merit_finite": 1}

    with pytest.raises(StateInvariantError, match="lane_perm_bijective"):
        check_invariants(w.params, st.replace(
            lane_perm=st.lane_perm.at[0].set(st.lane_perm[1])))

    if st.resources.shape[0]:
        bad = st.replace(resources=st.resources.at[0].set(-1.0))
        assert int(audit_state(w.params, bad)["resources_nonneg"]) == 1

    # save-path integration: a corrupt state refuses to checkpoint
    w.state = st.replace(merit=st.merit.at[cell].set(jnp.inf))
    with pytest.raises(StateInvariantError):
        w.save_checkpoint(str(tmp_path / "ck"))


def test_datfile_append_on_resume(tmp_path):
    """Inside utils/output.append_existing(), reopening an existing .dat
    file appends (no truncation, no duplicate header); fresh files still
    get their header.  World.resume arms this so a resumed run extends
    the preempted run's rows."""
    from avida_tpu.utils import output as output_mod

    path = str(tmp_path / "x.dat")
    f = output_mod.DatFile(path, "T", ["col a"])
    f.write_row([1, 2.5])
    f.close()

    with output_mod.append_existing():
        f2 = output_mod.DatFile(path, "T", ["col a"])
        f2.write_row([2, 3.5])
        f2.close()
        fresh = output_mod.DatFile(str(tmp_path / "y.dat"), "T", ["col a"])
        fresh.close()

    lines = open(path).read().splitlines()
    assert lines.count("# T") == 1                 # single header block
    rows = [l for l in lines if l and not l.startswith("#")]
    assert rows == ["1 2.5 ", "2 3.5 "]
    assert open(str(tmp_path / "y.dat")).read().startswith("# T")

    # outside the context, the historical truncate-on-open contract holds
    f3 = output_mod.DatFile(path, "T", ["col a"])
    f3.close()
    rows = [l for l in open(path).read().splitlines()
            if l and not l.startswith("#")]
    assert rows == []


def test_trim_stale_rows_on_resume(tmp_path):
    """Rows PAST the restored update are trimmed before append-mode
    reopening (a crash that outran the last auto-save would otherwise
    duplicate those updates after resume); non-numeric rows and headers
    are kept; telemetry.jsonl gets the analogous treatment including a
    torn tail line."""
    from avida_tpu.observability.runlog import trim_update_records
    from avida_tpu.utils import output as output_mod

    d = str(tmp_path)
    with open(os.path.join(d, "average.dat"), "w") as f:
        f.write("# header\n\n5 1.0 \n10 2.0 \n15 3.0 \n20 4.0 \n")
    with open(os.path.join(d, "notes.txt"), "w") as f:
        f.write("15 not a dat file\n")
    output_mod.trim_dat_rows(d, 10)
    rows = [l.split()[0] for l in open(os.path.join(d, "average.dat"))
            if l.strip() and not l.startswith("#")]
    # STRICT cutoff: the resumed run re-fires events at the restored
    # update, so the row labeled 10 itself must go too
    assert rows == ["5"]
    assert open(os.path.join(d, "notes.txt")).read() == "15 not a dat file\n"

    tj = os.path.join(d, "telemetry.jsonl")
    with open(tj, "w") as f:
        f.write(json.dumps({"record": "meta", "seed": 1}) + "\n")
        f.write(json.dumps({"record": "update", "update": 9}) + "\n")
        f.write(json.dumps({"record": "update", "update": 11}) + "\n")
        f.write('{"record": "update", "upda')        # torn tail
    trim_update_records(tj, 10)
    recs = [json.loads(l) for l in open(tj)]
    assert [r.get("update") for r in recs] == [None, 9]
    trim_update_records(os.path.join(d, "missing.jsonl"), 10)   # no-op


def test_same_update_resave_keeps_a_recoverable_generation(tmp_path):
    """A same-update re-save must never pass through a state with zero
    recoverable generations: the old generation is moved aside before
    the new one is renamed in, and restore_candidates() still finds the
    aside if a crash lands inside that window."""
    base = str(tmp_path / "ck")
    ckpt_mod.write_generation(base, 7, _arrays(), {"v": 1}, keep=2)
    path = ckpt_mod.write_generation(base, 7, _arrays(), {"v": 2}, keep=2)
    assert ckpt_mod.verify_generation(path)["host"] == {"v": 2}
    assert len(ckpt_mod.list_generations(base)) == 1

    # simulate the crash window: published generation moved aside, new
    # one never renamed in
    aside = os.path.join(base, ".old-ckpt-000000000007.999")
    os.rename(path, aside)
    assert ckpt_mod.list_generations(base) == []
    found, manifest = ckpt_mod.latest_valid(base)
    assert found == aside and manifest["host"] == {"v": 2}
    # ...and the next successful save sweeps the aside
    ckpt_mod.write_generation(base, 8, _arrays(), {}, keep=2)
    assert not [d for d in os.listdir(base) if d.startswith(".old-")]
