"""Packed-resident update chunk (ops/packed_chunk.py): the round-6
tentpole.  The contract under test is BIT-EXACTNESS of the resident-plane
scan against the per-update pack/unpack path:

    unpack(scan_packed(pack(st), K)) == update_step^K(st)

for every eligible configuration -- mutations on, births crossing chunk
boundaries, the flight recorder armed, TPU_LANE_PERM>1 present (the
permutation is superseded: identity on BOTH paths), and sharded vs
unsharded kernel launches.  Fast tier covers the routing predicate and
the packed word-plane algebra (SWAR byte ops, the divide-mutation port);
the kernel-driving trajectory tests run in Pallas interpret mode and are
slow-tier, like tests/test_pallas.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.ops import packed_chunk
from avida_tpu.world import World


def _mk_world(seeds=(10, 11, 20, 21, 27), overrides=(), world=6,
              max_memory=200):
    cfg = AvidaConfig()
    cfg.WORLD_X = world
    cfg.WORLD_Y = world
    cfg.TPU_MAX_MEMORY = max_memory
    cfg.RANDOM_SEED = 3
    cfg.AVE_TIME_SLICE = 120
    cfg.TPU_USE_PALLAS = 1            # interpret mode on CPU
    cfg.set("TPU_SYSTEMATICS", 0)
    for k, v in overrides:
        cfg.set(k, v)
    w = World(cfg=cfg)
    for c in seeds:
        w.inject(cell=c)
    return w


def _assert_states_equal(sa, sb, skip=()):
    for name in sa.__dataclass_fields__:
        a, b = getattr(sa, name), getattr(sb, name)
        if a is None or name in skip:
            continue
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"field {name}")


def _per_update(params, st, neighbors, run_key, K, u0=0):
    from avida_tpu.ops.update import update_step
    st = jax.tree.map(jnp.copy, st)
    for u in range(u0, u0 + K):
        st, _ = update_step(params, st, jax.random.fold_in(run_key, u),
                            neighbors, jnp.int32(u))
    return st


# ------------------------------------------------------------ fast tier

def test_active_routing():
    """The static predicate engages exactly for the supported envelope
    and every exclusion knob routes back to the per-update path."""
    w = _mk_world(seeds=(18,))
    assert packed_chunk.active(w.params, w.state)
    # the off switch
    assert not packed_chunk.active(
        w.params.replace(packed_chunk=0), w.state)
    # XLA path (TPU_USE_PALLAS=2)
    assert not packed_chunk.active(w.params.replace(use_pallas=2), w.state)
    # non-torus geometry loses the roll-based flush
    assert not packed_chunk.active(w.params.replace(geometry=1), w.state)
    # per-site point mutations / slip mutations stay canonical
    assert not packed_chunk.active(
        w.params.replace(point_mut_prob=0.001), w.state)
    assert not packed_chunk.active(
        w.params.replace(divide_slip_prob=0.05), w.state)
    # a populated newborn ring (systematics on) keeps the per-update path
    w2 = _mk_world(seeds=(18,), overrides=(("TPU_SYSTEMATICS", 1),))
    assert not packed_chunk.active(w2.params, w2.state)


def test_pack_unpack_chunk_roundtrip():
    """unpack_chunk(pack_chunk(st)) is the identity on every canonical
    field (the genome plane rides the chunk; kernel-read-only rows
    restore through restore_ro)."""
    w = _mk_world(seeds=(7, 8, 21))
    st = w.state
    st2 = packed_chunk.unpack_chunk(w.params,
                                    packed_chunk.pack_chunk(w.params, st))
    _assert_states_equal(st, st2)


def test_pk_byte_helpers_match_byte_ops():
    """The SWAR word-plane helpers reproduce plain byte-array algebra:
    set-byte, funnel shifts, range masks."""
    from avida_tpu.ops.birth import (_pk_range_mask, _pk_set_byte,
                                     _pk_shift_l1, _pk_shift_r1)
    from avida_tpu.ops.pallas_cycles import _pack_words, _unpack_words

    rng = np.random.default_rng(0)
    n, L = 13, 64
    LP = L // 4
    by = rng.integers(0, 64, (n, L), np.uint8)
    plane = _pack_words(jnp.asarray(by), L).T           # [LP, n]

    # funnel shifts
    np.testing.assert_array_equal(
        np.asarray(_unpack_words(_pk_shift_r1(plane).T, L)),
        np.concatenate([np.zeros((n, 1), np.uint8), by[:, :-1]], axis=1))
    np.testing.assert_array_equal(
        np.asarray(_unpack_words(_pk_shift_l1(plane).T, L)),
        np.concatenate([by[:, 1:], np.zeros((n, 1), np.uint8)], axis=1))

    # set-byte at per-lane positions
    pos = jnp.asarray(rng.integers(0, L, n), jnp.int32)
    val = jnp.asarray(rng.integers(0, 64, n), jnp.int32)
    got = np.asarray(_unpack_words(_pk_set_byte(plane, pos, val).T, L))
    want = by.copy()
    want[np.arange(n), np.asarray(pos)] = np.asarray(val, np.uint8)
    np.testing.assert_array_equal(got, want)

    # range mask == per-byte [lo, hi) selection
    lo = jnp.asarray(rng.integers(0, L, n), jnp.int32)
    hi = jnp.asarray(rng.integers(0, L + 4, n), jnp.int32)
    m = _pk_range_mask(LP, lo, hi)
    got = np.asarray(_unpack_words((plane & m).T, L))
    cols = np.arange(L)[None, :]
    want = np.where((cols >= np.asarray(lo)[:, None])
                    & (cols < np.asarray(hi)[:, None]), by, 0)
    np.testing.assert_array_equal(got, want)


def test_pk_extract_offspring_matches_canonical():
    """The packed divide-mutation port consumes the identical PRNG
    stream: same key, same draws, same offspring -- across substitution,
    insertion, deletion, DIV_MUT and COPY_INS/DEL branches."""
    from avida_tpu.config.environment import default_logic9_environment
    from avida_tpu.config.instset import default_instset
    from avida_tpu.core.state import make_world_params, zeros_population
    from avida_tpu.ops.birth import _pk_extract_offspring
    from avida_tpu.ops.interpreter import extract_offspring
    from avida_tpu.ops.pallas_cycles import _pack_words, _unpack_words

    cfg = AvidaConfig()
    cfg.WORLD_X = 4
    cfg.WORLD_Y = 4
    cfg.TPU_MAX_MEMORY = 64
    cfg.DIV_MUT_PROB = 0.02
    cfg.set("COPY_INS_PROB", 0.01)
    cfg.set("COPY_DEL_PROB", 0.01)
    params = make_world_params(cfg, default_instset(),
                               default_logic9_environment())
    n, L = 16, 64
    rng = np.random.default_rng(5)
    st = zeros_population(n, L, params.num_reactions)
    off_len = rng.integers(10, 40, n).astype(np.int32)
    off = rng.integers(0, 26, (n, L)).astype(np.uint8)
    off[np.arange(L)[None, :] >= off_len[:, None]] = 0
    st = st.replace(
        off_tape=jnp.asarray(off),
        off_len=jnp.asarray(off_len),
        genome_len=jnp.asarray(rng.integers(10, 40, n).astype(np.int32)),
        divide_pending=jnp.asarray(rng.random(n) < 0.8),
        alive=jnp.ones(n, bool),
    )
    key = jax.random.key(99)
    want_off, want_len = extract_offspring(params, st, key,
                                           use_off_tape=True)
    got_w, got_len = _pk_extract_offspring(
        params, key, _pack_words(jnp.asarray(off), L).T,
        st.off_len, st.genome_len, st.divide_pending)
    np.testing.assert_array_equal(np.asarray(got_len), np.asarray(want_len))
    np.testing.assert_array_equal(
        np.asarray(_unpack_words(got_w.T, L)).astype(np.int8),
        np.asarray(want_off))
    # mutations actually fired (otherwise this test proves nothing)
    assert not np.array_equal(np.asarray(want_len), off_len)


# ------------------------------------------------------------ slow tier

@pytest.mark.slow
def test_packed_scan_matches_per_update_mutations_on():
    """THE tentpole contract: a packed-resident K-update scan is
    bit-exact vs K per-update update_step calls, with the full default
    mutation battery on (copy substitutions + divide ins/del riding the
    packed-native flush)."""
    from avida_tpu.ops.update import update_scan

    w = _mk_world()
    params, nb, st0 = w.params, w.neighbors, w.state
    assert packed_chunk.active(params, st0)
    run_key = jax.random.key(123)
    K = 10
    ref = _per_update(params, st0, nb, run_key, K)
    got, _ = update_scan(params, jax.tree.map(jnp.copy, st0), K, run_key,
                         nb, jnp.int32(0))
    _assert_states_equal(ref, got)
    assert int(np.asarray(ref.num_divides).sum()) > 0, \
        "no divide happened -- the flush was never exercised"


@pytest.mark.slow
def test_packed_chunk_boundary_births_bit_exact():
    """Births landing ACROSS a chunk boundary: splitting the scan at any
    point (pending divides crossing the unpack/repack) changes nothing."""
    from avida_tpu.ops.update import update_scan

    w = _mk_world()
    params, nb, st0 = w.params, w.neighbors, w.state
    run_key = jax.random.key(7)
    K = 12
    ref, _ = update_scan(params, jax.tree.map(jnp.copy, st0), K, run_key,
                         nb, jnp.int32(0))
    for split in (1, 5, 7):
        st1, _ = update_scan(params, jax.tree.map(jnp.copy, st0), split,
                             run_key, nb, jnp.int32(0))
        st2, _ = update_scan(params, st1, K - split, run_key, nb,
                             jnp.int32(split))
        _assert_states_equal(ref, st2)
    assert int(np.asarray(ref.num_divides).sum()) >= 10


@pytest.mark.slow
def test_packed_supersedes_lane_perm_bit_exact():
    """TPU_LANE_PERM > 1 with packed residency: the permutation is
    superseded on BOTH paths (identity lanes -- perm_phase's mid-chunk /
    early refresh schedule never engages), so packed-vs-per-update
    bit-exactness holds and lane_perm stays identity throughout."""
    from avida_tpu.ops.update import update_scan

    w = _mk_world(overrides=(("TPU_LANE_PERM", 2),
                             ("TPU_LANE_PERM_MIN_UTIL", 0.99)))
    params, nb, st0 = w.params, w.neighbors, w.state
    assert params.lane_perm_k == 2
    assert packed_chunk.active(params, st0)
    run_key = jax.random.key(7)
    K = 10
    ref = _per_update(params, st0, nb, run_key, K)
    got, _ = update_scan(params, jax.tree.map(jnp.copy, st0), K, run_key,
                         nb, jnp.int32(0))
    _assert_states_equal(ref, got)
    n = params.num_cells
    assert np.array_equal(np.asarray(got.lane_perm), np.arange(n))
    assert np.array_equal(np.asarray(got.lane_inv), np.arange(n))


@pytest.mark.slow
def test_packed_matches_xla_engine():
    """Cross-ENGINE equivalence: the packed-resident pallas scan equals
    the XLA micro-step engine trajectory (mutation-free so no PRNG-
    stream divergence; lane bookkeeping excluded as in test_pallas)."""
    from avida_tpu.ops.update import update_scan

    muts = (("COPY_MUT_PROB", 0.0), ("DIVIDE_INS_PROB", 0.0),
            ("DIVIDE_DEL_PROB", 0.0), ("SLICING_METHOD", 0),
            ("AVE_TIME_SLICE", 120))
    wp = _mk_world(overrides=muts)
    wx = _mk_world(overrides=muts + (("TPU_USE_PALLAS", 2),))
    assert packed_chunk.active(wp.params, wp.state)
    assert not packed_chunk.active(wx.params, wx.state)
    run_key = jax.random.key(42)
    K = 10
    got, _ = update_scan(wp.params, wp.state, K, run_key, wp.neighbors,
                         jnp.int32(0))
    ref = _per_update(wx.params, wx.state, wx.neighbors, run_key, K)
    _assert_states_equal(ref, got, skip={"lane_perm", "lane_inv"})


@pytest.mark.slow
@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
def test_packed_sharded_matches_unsharded():
    """TPU_KERNEL_SHARDS=2 vs 1 under packed residency: the shard_map'd
    kernel launches inside the resident chunk (and the GSPMD-sharded
    roll-based flush around them) reproduce the unsharded trajectory
    bit-for-bit, boundary-crossing births included (the seed cells sit
    on the shard-0/1 band boundary).  Mutation-free, as in
    tests/test_parallel.py (interpret-mode PRNG streams are
    lane-indexed)."""
    from avida_tpu.ops.update import update_scan
    from avida_tpu.parallel import (make_mesh, shard_neighbors,
                                    shard_population)

    muts = (("COPY_MUT_PROB", 0.0), ("DIVIDE_INS_PROB", 0.0),
            ("DIVIDE_DEL_PROB", 0.0), ("SLICING_METHOD", 0),
            ("AVE_TIME_SLICE", 100), ("TPU_MAX_STEPS_PER_UPDATE", 100))
    # 32x32 = 1024 cells: 512-lane blocks x 2 shards -- the live band
    # really spans both shards
    w1 = _mk_world(seeds=(511, 512), world=32,
                   overrides=muts + (("TPU_KERNEL_SHARDS", 1),))
    w2 = _mk_world(seeds=(511, 512), world=32,
                   overrides=muts + (("TPU_KERNEL_SHARDS", 2),))
    assert packed_chunk.active(w1.params, w1.state)
    run_key = jax.random.key(17)
    K = 6
    ref, _ = update_scan(w1.params, w1.state, K, run_key, w1.neighbors,
                         jnp.int32(0))
    mesh = make_mesh(jax.devices()[:2])
    got, _ = update_scan(w2.params, shard_population(w2.state, mesh), K,
                         run_key, shard_neighbors(w2.neighbors, mesh),
                         jnp.int32(0))
    _assert_states_equal(ref, got)
    assert int(np.asarray(ref.alive).sum()) > 2, "no birth -- lengthen"
