"""Fused packed-resident scan body + 5-bit genome shadow (round 14).

Two contracts ride on ops/packed_chunk.py's round-14 work:

  1. FUSED: with the flight recorder off, the packed scan body runs
     schedule/bank/stats in ROW space and the birth flush skips the
     per-update canonical-mirror refresh -- the mirrors go stale
     mid-chunk and are rebuilt once at the boundary.  The trajectory
     must stay bit-exact vs the legacy row-space body
     (TPU_PACKED_FUSED=0: fresh mirrors every update) and vs the XLA
     micro-step engine.

  2. BITS: TPU_PACKED_BITS=1 narrows the genome shadow plane to 5-bit
     codes, six per int32 word (the kernel never reads gen_t, so only
     pack/unpack and the flush's breed-true compare + newborn write
     touch the codec).  Trajectories -- and therefore checkpoints,
     which serialize the canonical state -- must be byte-identical
     with the codec on or off.

Fast tier: codec algebra, routing/reason strings, jaxpr-digest and
compile-cache-key knob coverage, footprint accounting.  Slow tier:
trajectory bit-exactness on solo and stacked-worlds legs (Pallas
interpret mode, like tests/test_packed_chunk.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.ops import packed_chunk, pallas_cycles
from avida_tpu.world import World

from tests.test_packed_chunk import (_assert_states_equal, _mk_world,
                                     _per_update)


def _small_params(**over):
    from avida_tpu.config.environment import default_logic9_environment
    from avida_tpu.config.instset import default_instset
    from avida_tpu.core.state import make_world_params

    cfg = AvidaConfig()
    cfg.WORLD_X = 6
    cfg.WORLD_Y = 6
    cfg.TPU_MAX_MEMORY = 64
    p = make_world_params(cfg, default_instset(),
                          default_logic9_environment())
    return p.replace(**over) if over else p


# ------------------------------------------------------------ fast tier

def test_words5_roundtrip_ragged():
    """The 5-bit codec is lossless on exactly the data the engine
    stores: opcode bytes < 32, zero beyond the genome length -- over
    ragged lengths, every packable opcode-count ceiling, and L values
    straddling the six-codes-per-word boundary."""
    rng = np.random.default_rng(14)
    for L in (6, 37, 64, 91, 200, 384):
        for num_insts in (2, 7, 26, 32):
            n = 17
            lens = rng.integers(0, L + 1, n)
            by = rng.integers(0, num_insts, (n, L)).astype(np.uint8)
            by[np.arange(L)[None, :] >= lens[:, None]] = 0
            words = pallas_cycles._pack_words5(jnp.asarray(by), L)
            assert words.shape == (n, pallas_cycles.words5(L))
            got = np.asarray(pallas_cycles._unpack_words5(words, L))
            np.testing.assert_array_equal(got, by)


def test_pk5_plane_helpers_match_codec():
    """The flush-side SWAR helpers agree with the codec: _pk_to_plane5
    re-packs a byte plane into the 5-bit layout, and _pk5_prefix_mask
    selects exactly the first `hi` codes of each lane."""
    from avida_tpu.ops.birth import _pk5_prefix_mask, _pk_to_plane5
    from avida_tpu.ops.pallas_cycles import _pack_words, _pack_words5

    rng = np.random.default_rng(5)
    n, L = 13, 88                       # LP=22 rows, L5=15 words
    L5 = pallas_cycles.words5(L)
    by = rng.integers(0, 32, (n, L)).astype(np.uint8)
    plane = _pack_words(jnp.asarray(by), L).T        # byte layout [LP, n]
    want = _pack_words5(jnp.asarray(by), L).T        # 5-bit layout [L5, n]
    np.testing.assert_array_equal(np.asarray(_pk_to_plane5(plane, L5)),
                                  np.asarray(want))

    hi = jnp.asarray(rng.integers(0, L + 5, n), jnp.int32)
    m = _pk5_prefix_mask(L5, hi)
    got = np.asarray(pallas_cycles._unpack_words5((want & m).T, L))
    keep = np.arange(L)[None, :] < np.asarray(hi)[:, None]
    np.testing.assert_array_equal(got, np.where(keep, by, 0))


def test_fused_and_bits_routing_reasons():
    """Every fused/bits exclusion names itself, and engine_report
    journals the sub-path the scan body will actually take -- including
    the loud armed-but-refused bits case."""
    p = _small_params()
    assert packed_chunk.fused_active(p)
    assert packed_chunk.fused_ineligible_reason(
        p.replace(packed_fused=0)) == "TPU_PACKED_FUSED=0"
    assert "flight recorder" in packed_chunk.fused_ineligible_reason(
        p.replace(trace_cap=64))

    assert packed_chunk.bits_ineligible_reason(p) == "TPU_PACKED_BITS=0"
    assert packed_chunk.bits_active(p.replace(packed_bits=1))
    big = p.replace(packed_bits=1, num_insts=33)
    assert "num_insts=33" in packed_chunk.bits_ineligible_reason(big)

    pe = p.replace(use_pallas=1)      # interpret mode: packed-eligible
    rep = packed_chunk.engine_report(pe)
    assert rep["engine"] == "packed" and rep["sub_path"] == "fused"
    assert rep["packed_bits"] == 0 and "bits_fallback_reason" not in rep
    rep = packed_chunk.engine_report(pe.replace(trace_cap=64))
    assert rep["sub_path"] == "row-space"
    assert "flight recorder" in rep["fused_fallback_reason"]
    rep = packed_chunk.engine_report(pe.replace(packed_bits=1))
    assert rep["packed_bits"] == 1
    rep = packed_chunk.engine_report(pe.replace(packed_bits=1,
                                                num_insts=33))
    assert rep["packed_bits"] == 0
    assert "num_insts=33" in rep["bits_fallback_reason"]
    rep = packed_chunk.engine_report(pe.replace(packed_chunk=0))
    assert rep["engine"] == "per-update"
    assert rep["fallback_reason"] == "TPU_PACKED_CHUNK=0"


def test_update_step_jaxpr_invariant_under_knobs():
    """update_step never routes packed, so arming TPU_PACKED_FUSED /
    TPU_PACKED_BITS must leave its traced program byte-identical --
    the scripts/check_jaxpr.py gate cannot move with these knobs."""
    import hashlib

    from avida_tpu.core.state import zeros_population
    from avida_tpu.ops import birth as birth_ops
    from avida_tpu.ops.update import update_step

    def digest(p):
        st = zeros_population(p.num_cells, p.max_memory, p.num_reactions)
        nb = jnp.asarray(birth_ops.neighbor_table(6, 6, p.geometry))
        jx = str(jax.make_jaxpr(
            lambda s, k, u: update_step(p, s, k, nb, u))(
                st, jax.random.key(0), jnp.int32(0)))
        return hashlib.sha256(jx.encode()).hexdigest()

    base = digest(_small_params())
    assert digest(_small_params(packed_fused=0)) == base
    assert digest(_small_params(packed_bits=1)) == base
    assert digest(_small_params(packed_fused=0, packed_bits=1)) == base


def test_cache_key_covers_knobs():
    """The AOT program-cache key must split on every program-affecting
    static -- a cached fused program must never serve a legacy-body
    request (or a bits=1 program a bits=0 one)."""
    from avida_tpu.utils import compilecache

    dyn = (jnp.zeros((4,), jnp.int32),)
    keys = {compilecache.cache_key("chunk", _small_params(**ov), 25, dyn)
            for ov in ({}, {"packed_fused": 0}, {"packed_bits": 1},
                       {"packed_fused": 0, "packed_bits": 1})}
    assert len(keys) == 4


def test_packed_planes_footprint_accounting():
    """The residency numbers the bench/profiler publish: the 5-bit
    codec narrows ONLY gen_t (ceil(L/6) words vs L/4), saved_bytes is
    the exact delta, and the bits-off comparator equals the bits-off
    total.  An armed-but-refused config reports why."""
    from avida_tpu.observability import profiler

    p = _small_params(use_pallas=1)
    n = int(p.num_cells)
    off = profiler.packed_planes_footprint(p, n)
    on = profiler.packed_planes_footprint(p.replace(packed_bits=1), n)
    assert off["packed_bits"] == 0 and on["packed_bits"] == 1
    assert off["saved_bytes"] == 0
    assert off["total_bytes"] == off["unpacked_total_bytes"] \
        == on["unpacked_total_bytes"]
    assert on["saved_bytes"] == off["total_bytes"] - on["total_bytes"] > 0
    for name in ("tape_t", "off_t", "ivec", "fvec"):
        assert on["planes"][name] == off["planes"][name]
    assert on["planes"]["gen_t"]["rows"] < off["planes"]["gen_t"]["rows"]
    assert on["bytes_per_org"] < off["bytes_per_org"]

    refused = profiler.packed_planes_footprint(
        p.replace(packed_bits=1, num_insts=40), n)
    assert refused["saved_bytes"] == 0
    assert "num_insts=40" in refused["bits_fallback_reason"]


def test_state_footprint_reports_packed_planes():
    """state_footprint(params=...) carries the resident-plane block on
    packed-eligible configs (what the run actually keeps in HBM during
    a chunk), and omits it when the engine routes per-update."""
    from avida_tpu.observability import profiler

    w = _mk_world(seeds=(7,))
    fp = profiler.state_footprint(w.state, params=w.params)
    assert "packed_planes" in fp
    assert fp["packed_planes"]["total_bytes"] > 0
    fp = profiler.state_footprint(
        w.state, params=w.params.replace(packed_chunk=0))
    assert "packed_planes" not in fp


# ------------------------------------------------------------ slow tier

@pytest.mark.slow
def test_fused_matches_legacy_and_per_update():
    """THE round-14 contract: the fused body (row-space phases, stale
    mirrors, flush skips the refresh) is bit-exact vs the legacy packed
    body (TPU_PACKED_FUSED=0) and vs the per-update reference, full
    default mutation battery on."""
    from avida_tpu.ops.update import update_scan

    w = _mk_world()
    wl = _mk_world(overrides=(("TPU_PACKED_FUSED", 0),))
    assert packed_chunk.fused_active(w.params)
    assert not packed_chunk.fused_active(wl.params)
    run_key = jax.random.key(123)
    K = 10
    ref = _per_update(w.params, w.state, w.neighbors, run_key, K)
    got, _ = update_scan(w.params, jax.tree.map(jnp.copy, w.state), K,
                         run_key, w.neighbors, jnp.int32(0))
    leg, _ = update_scan(wl.params, jax.tree.map(jnp.copy, wl.state), K,
                         run_key, wl.neighbors, jnp.int32(0))
    _assert_states_equal(ref, got)
    _assert_states_equal(leg, got)
    assert int(np.asarray(ref.num_divides).sum()) > 0, \
        "no divide -- the fused flush was never exercised"


@pytest.mark.slow
def test_bits5_scan_bit_exact():
    """TPU_PACKED_BITS=1 changes ONLY the resident encoding: the
    canonical trajectory -- and with it any checkpoint serialized from
    it -- is byte-identical with the codec on or off, mutations on
    (divide ins/del exercise the ragged prefix mask and the 5-bit
    newborn write)."""
    from avida_tpu.ops.update import update_scan

    w0 = _mk_world()
    w1 = _mk_world(overrides=(("TPU_PACKED_BITS", 1),))
    assert packed_chunk.bits_active(w1.params)
    assert not packed_chunk.bits_active(w0.params)
    run_key = jax.random.key(77)
    K = 12
    a, _ = update_scan(w0.params, jax.tree.map(jnp.copy, w0.state), K,
                       run_key, w0.neighbors, jnp.int32(0))
    b, _ = update_scan(w1.params, jax.tree.map(jnp.copy, w1.state), K,
                       run_key, w1.neighbors, jnp.int32(0))
    _assert_states_equal(a, b)
    assert int(np.asarray(a.num_divides).sum()) > 0, \
        "no divide -- the 5-bit breed-true/newborn path was never hit"


@pytest.mark.slow
def test_fused_bits_worlds_stacked_bit_exact():
    """Stacked-worlds leg: W=2 worlds through update_step_packed_worlds
    with fused + bits5 armed equal each world's SOLO packed scan -- the
    serve-batch shape of both round-14 axes."""
    wa = _mk_world(seeds=(10, 11, 20), overrides=(("TPU_PACKED_BITS", 1),))
    wb = _mk_world(seeds=(21, 27, 30), overrides=(("TPU_PACKED_BITS", 1),))
    params, nb = wa.params, wa.neighbors
    assert packed_chunk.fused_active(params)
    assert packed_chunk.bits_active(params)
    K = 6
    base = [jax.random.key(900 + i) for i in range(2)]

    def solo(st, bkey):
        pc = packed_chunk.pack_chunk(params, st)
        for u in range(K):
            pc, _ = packed_chunk.update_step_packed(
                params, pc, jax.random.fold_in(bkey, u), nb, jnp.int32(u))
        return packed_chunk.unpack_chunk(params, pc)

    refs = [solo(jax.tree.map(jnp.copy, w.state), k)
            for w, k in zip((wa, wb), base)]

    bst = jax.tree.map(lambda a, b: jnp.stack([a, b]), wa.state, wb.state)
    pw = packed_chunk.pack_worlds(params, bst)
    for u in range(K):
        keys = jnp.stack([jax.random.fold_in(k, u) for k in base])
        pw, _, _ = packed_chunk.update_step_packed_worlds(
            params, pw, keys, nb, jnp.int32(u))
    got = packed_chunk.unpack_worlds(params, pw)
    for i, ref in enumerate(refs):
        _assert_states_equal(ref, jax.tree.map(lambda x: x[i], got))
    assert sum(int(np.asarray(r.num_divides).sum()) for r in refs) > 0
