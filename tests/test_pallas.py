"""Pallas cycle-kernel equivalence: kernel path == XLA micro-step path.

The VMEM-resident kernel (ops/pallas_cycles.py) re-implements the heads
hardware cycle loop; this test proves the two engines produce BIT-IDENTICAL
population state over multiple full updates covering a complete gestation
including h-divide and the birth flush (VERDICT r2 item 1).  Mutations are
off and budgets fixed (SLICING_METHOD 0) so no PRNG stream enters the cycle
loop; every other source of state evolution (copy loop, label search, IO /
task rewards, divide viability, phenotype DivideReset, death, birth scatter)
is exercised by evolving the stock ancestor to its first offspring and
beyond.  Runs in Pallas interpret mode on CPU; the same kernel runs natively
on TPU (bench.py measures through it).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.ops.update import update_step, use_pallas_path
from avida_tpu.world import World


def _mk_world(use_pallas: int) -> World:
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    # >= ~3x ancestor length (room for h-alloc); deliberately NOT a multiple
    # of the kernel CHUNK so the L-padding path in _dims is exercised
    cfg.TPU_MAX_MEMORY = 200
    cfg.RANDOM_SEED = 11
    cfg.COPY_MUT_PROB = 0.0          # no PRNG inside the cycle loop
    cfg.DIVIDE_INS_PROB = 0.0
    cfg.DIVIDE_DEL_PROB = 0.0
    cfg.SLICING_METHOD = 0           # constant budgets: no scheduler PRNG
    cfg.AVE_TIME_SLICE = 100         # gestation (~389 cycles) in ~4 updates
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    cfg.TPU_USE_PALLAS = use_pallas
    cfg.set("TPU_SYSTEMATICS", 0)
    w = World(cfg=cfg)
    w.inject()
    return w


def test_pallas_path_selected():
    w = _mk_world(1)
    assert use_pallas_path(w.params)
    w2 = _mk_world(2)
    assert not use_pallas_path(w2.params)


def test_kernel_bit_equivalence_through_gestation():
    wk = _mk_world(1)   # kernel (interpret mode on CPU)
    wx = _mk_world(2)   # XLA micro-step loop
    n_updates = 8       # first divide ~update 4; births + second gestation

    saw_divide = False
    for u in range(n_updates):
        wk.run_update()
        wx.run_update()
        wk.update += 1
        wx.update += 1
        sk, sx = wk.state, wx.state
        if bool(np.asarray(sx.num_divides).sum() > 0):
            saw_divide = True
        for name in sk.__dataclass_fields__:
            a = np.asarray(getattr(sk, name))
            b = np.asarray(getattr(sx, name))
            np.testing.assert_array_equal(
                a, b, err_msg=f"field {name} diverged at update {u}")
    assert saw_divide, "test never exercised h-divide; lengthen the run"
    assert int(np.asarray(wx.state.alive).sum()) > 1, \
        "no offspring was ever born; birth flush unexercised"
