"""Pallas cycle-kernel equivalence: kernel path == XLA micro-step path.

The VMEM-resident kernel (ops/pallas_cycles.py) re-implements the heads
hardware cycle loop; this test proves the two engines produce BIT-IDENTICAL
population state over multiple full updates covering a complete gestation
including h-divide and the birth flush (VERDICT r2 item 1).  Mutations are
off and budgets fixed (SLICING_METHOD 0) so no PRNG stream enters the cycle
loop; every other source of state evolution (copy loop, label search, IO /
task rewards, divide viability, phenotype DivideReset, death, birth scatter)
is exercised by evolving the stock ancestor to its first offspring and
beyond.  Runs in Pallas interpret mode on CPU; the same kernel runs natively
on TPU (bench.py measures through it).
"""

from __future__ import annotations


import jax.numpy as jnp
import numpy as np
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.ops.update import use_pallas_path
from avida_tpu.world import World

pytestmark = pytest.mark.slow


def _mk_world(use_pallas: int) -> World:
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    # >= ~3x ancestor length (room for h-alloc); deliberately NOT a multiple
    # of the kernel CHUNK so the L-padding path in _dims is exercised
    cfg.TPU_MAX_MEMORY = 200
    cfg.RANDOM_SEED = 11
    cfg.COPY_MUT_PROB = 0.0          # no PRNG inside the cycle loop
    cfg.DIVIDE_INS_PROB = 0.0
    cfg.DIVIDE_DEL_PROB = 0.0
    cfg.SLICING_METHOD = 0           # constant budgets: no scheduler PRNG
    cfg.AVE_TIME_SLICE = 100         # gestation (~389 cycles) in ~4 updates
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    cfg.TPU_USE_PALLAS = use_pallas
    cfg.set("TPU_SYSTEMATICS", 0)
    w = World(cfg=cfg)
    w.inject()
    return w


def test_pallas_path_selected():
    w = _mk_world(1)
    assert use_pallas_path(w.params)
    w2 = _mk_world(2)
    assert not use_pallas_path(w2.params)


# engine-internal kernel-lane bookkeeping: refreshed on the pallas path
# only (ops/update.perm_phase), identity on the XLA path -- transparent
# to physics, so cross-ENGINE comparisons skip it (same-engine sharding
# comparisons in tests/test_parallel.py still cover it exactly)
_ENGINE_INTERNAL = {"lane_perm", "lane_inv"}


def test_kernel_bit_equivalence_through_gestation():
    wk = _mk_world(1)   # kernel (interpret mode on CPU)
    wx = _mk_world(2)   # XLA micro-step loop
    n_updates = 8       # first divide ~update 4; births + second gestation

    saw_divide = False
    for u in range(n_updates):
        wk.run_update()
        wx.run_update()
        wk.update += 1
        wx.update += 1
        sk, sx = wk.state, wx.state
        if bool(np.asarray(sx.num_divides).sum() > 0):
            saw_divide = True
        for name in sk.__dataclass_fields__:
            if name in _ENGINE_INTERNAL:
                continue
            a = np.asarray(getattr(sk, name))
            b = np.asarray(getattr(sx, name))
            np.testing.assert_array_equal(
                a, b, err_msg=f"field {name} diverged at update {u}")
    assert saw_divide, "test never exercised h-divide; lengthen the run"
    assert int(np.asarray(wx.state.alive).sum()) > 1, \
        "no offspring was ever born; birth flush unexercised"


def _mk_world_is(use_pallas: int, instset_name: str = "",
                 instset_mut=None) -> World:
    """_mk_world with an instruction-set override (name routed through
    cfg.INST_SET) or an in-place instset mutator."""
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.TPU_MAX_MEMORY = 200
    cfg.RANDOM_SEED = 11
    cfg.COPY_MUT_PROB = 0.0
    cfg.DIVIDE_INS_PROB = 0.0
    cfg.DIVIDE_DEL_PROB = 0.0
    cfg.SLICING_METHOD = 0
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    cfg.TPU_USE_PALLAS = use_pallas
    if instset_name:
        cfg.INST_SET = instset_name
    cfg.set("TPU_SYSTEMATICS", 0)
    w = World(cfg=cfg)
    if instset_mut is not None:
        from avida_tpu.core.state import make_world_params
        instset_mut(w.instset)
        w.params = make_world_params(w.cfg, w.instset, w.environment)
    w.inject()
    return w


def _assert_equivalent(wk, wx, n_updates=8, need_divide=True):
    saw_divide = False
    for u in range(n_updates):
        wk.run_update()
        wx.run_update()
        wk.update += 1
        wx.update += 1
        sk, sx = wk.state, wx.state
        if bool(np.asarray(sx.num_divides).sum() > 0):
            saw_divide = True
        for name in sk.__dataclass_fields__:
            if name in _ENGINE_INTERNAL:
                continue
            a = np.asarray(getattr(sk, name))
            b = np.asarray(getattr(sx, name))
            np.testing.assert_array_equal(
                a, b, err_msg=f"field {name} diverged at update {u}")
    if need_divide:
        assert saw_divide, "run too short to exercise h-divide"


def test_kernel_equivalence_with_instruction_costs():
    """Round-5 eligibility widening: the in-kernel cost engine (cost +
    ft_cost) must match the XLA interpreter bit-for-bit through a full
    gestation (ref SingleProcess_PayPreCosts, cHardwareBase.cc:1241)."""
    def add_costs(s):
        s.cost[s.opcode("inc")] = 3
        s.cost[s.opcode("h-copy")] = 2
        s.ft_cost[s.opcode("h-alloc")] = 5
    wk = _mk_world_is(1, instset_mut=add_costs)
    wx = _mk_world_is(2, instset_mut=add_costs)
    _assert_equivalent(wk, wx, n_updates=10)


def test_kernel_equivalence_divide_sex():
    """Divide-sex now runs in-kernel (off_sex recorded at the divide
    cycle; pairing/recombination stay in the shared birth flush)."""
    wk = _mk_world_is(1, instset_name="heads-sex")
    wx = _mk_world_is(2, instset_name="heads-sex")
    _assert_equivalent(wk, wx, n_updates=10, need_divide=False)
    assert bool(np.asarray(wx.state.divide_pending).any()) or \
        bool(np.asarray(wx.state.off_sex).any()) or \
        int(np.asarray(wx.state.num_divides).sum()) > 0


def test_kernel_prob_fail_suppresses_in_kernel():
    """prob_fail=1 on inc: the kernel must suppress the effect while
    still charging time (PRNG streams differ between engines, so this is
    a semantic check, not bit-equivalence)."""
    def fail_inc(s):
        s.prob_fail[s.opcode("inc")] = 1.0
    wk = _mk_world_is(1, instset_mut=fail_inc)
    wk.run_update()
    wk.update += 1
    st = wk.state
    alive0 = np.asarray(st.alive)
    assert alive0.any()
    # cycles still consumed (time charged on failures too)
    assert int(np.asarray(st.time_used)[alive0].max()) == 100
    # the ancestor's copy loop does not depend on inc: replication
    # proceeds through the suppressed instruction over a few more updates
    for _ in range(4):
        wk.run_update()
        wk.update += 1
    assert int(np.asarray(wk.state.alive).sum()) >= 2


def _mk_world_lane(use_pallas: int, lane_perm: int) -> World:
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.TPU_MAX_MEMORY = 200
    cfg.RANDOM_SEED = 11
    cfg.COPY_MUT_PROB = 0.0
    cfg.DIVIDE_INS_PROB = 0.0
    cfg.DIVIDE_DEL_PROB = 0.0
    cfg.SLICING_METHOD = 0
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    cfg.TPU_USE_PALLAS = use_pallas
    cfg.set("TPU_LANE_PERM", lane_perm)
    cfg.set("TPU_SYSTEMATICS", 0)
    # these tests target the budget-sort lane permutation specifically;
    # packed residency (round 6) would supersede it (identity lanes), so
    # pin it off here (tests/test_packed_chunk.py covers that path)
    cfg.set("TPU_PACKED_CHUNK", 0)
    w = World(cfg=cfg)
    w.inject()
    return w


def test_kernel_equivalence_under_lane_permutation():
    """Budget-aware lane packing (TPU_LANE_PERM): pallas-vs-XLA
    bit-equivalence must hold with the permutation ACTIVE.  On a mostly-
    empty world the budget sort is strongly non-identity (dead lanes
    grant 0 cycles and sort ahead of the live ones), so this exercises
    real permuted packing, not a no-op."""
    wk = _mk_world_lane(1, lane_perm=1)
    wx = _mk_world_lane(2, lane_perm=1)
    _assert_equivalent(wk, wx, n_updates=8)
    # the permutation really is non-identity mid-run
    n = wk.params.num_cells
    assert not np.array_equal(np.asarray(wk.state.lane_perm), np.arange(n))


def test_kernel_equivalence_identity_permutation():
    """TPU_LANE_PERM=0: identity lanes, the pre-permutation packing."""
    wk = _mk_world_lane(1, lane_perm=0)
    wx = _mk_world_lane(2, lane_perm=0)
    _assert_equivalent(wk, wx, n_updates=8)
    n = wk.params.num_cells
    assert np.array_equal(np.asarray(wk.state.lane_perm), np.arange(n))


def test_pack_unpack_roundtrip_under_permutation():
    """pack_state(perm) . unpack_state(inv) is the identity on every
    kernel-covered field, for an arbitrary (non-sorted) permutation."""
    from avida_tpu.ops import pallas_cycles

    w = _mk_world(2)
    for _ in range(5):           # evolve some nontrivial state
        w.run_update()
        w.update += 1
    st = w.state
    n = w.params.num_cells
    rng = np.random.default_rng(7)
    perm = jnp.asarray(rng.permutation(n).astype(np.int32))
    inv = jnp.zeros(n, jnp.int32).at[perm].set(jnp.arange(n, dtype=jnp.int32))
    granted = jnp.where(st.alive, 100, 0).astype(jnp.int32)

    packed = pallas_cycles.pack_state(w.params, st, granted, perm, 1)
    st2 = pallas_cycles.unpack_state(w.params, st, packed, inv)
    for name in st.__dataclass_fields__:
        np.testing.assert_array_equal(
            np.asarray(getattr(st, name)), np.asarray(getattr(st2, name)),
            err_msg=f"field {name} not restored through permuted pack")


def test_widened_eligibility():
    from avida_tpu.ops.pallas_cycles import eligible
    from avida_tpu.config.instset import default_instset, heads_sex_instset
    from avida_tpu.config.environment import default_logic9_environment

    def params_for(instset=None, **cfg_kw):
        from avida_tpu.core.state import make_world_params
        cfg = AvidaConfig()
        cfg.WORLD_X = 4
        cfg.WORLD_Y = 4
        for k, v in cfg_kw.items():
            cfg.set(k, v)
        return make_world_params(cfg, instset or default_instset(),
                                 default_logic9_environment())

    s = default_instset()
    s.cost[s.opcode("inc")] = 3
    assert eligible(params_for(instset=s))          # costs now in-kernel
    s2 = default_instset()
    s2.redundancy[0] = 5.0
    assert eligible(params_for(instset=s2))         # weighted mutations
    s3 = default_instset()
    s3.prob_fail[s3.opcode("inc")] = 0.5
    assert eligible(params_for(instset=s3))         # prob_fail
    assert eligible(params_for(instset=heads_sex_instset()))  # divide-sex
    assert not eligible(params_for(ENERGY_ENABLED=1))  # energy still out
