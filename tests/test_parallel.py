"""Multi-device sharding: sharded update == unsharded update, bit-for-bit.

The SPMD story (avida_tpu/parallel/mesh.py) replaces avida-mp's one-world-
per-MPI-rank scaling (cMultiProcessWorld.cc:142-310) with a single world
sharded over the cell axis.  Because the update step is a pure function and
GSPMD only changes the *placement* of the computation, the sharded program
must produce bit-identical results to the single-device one — this is the
determinism property SURVEY.md §5 requires in place of the reference's
sorted-MPI-receive ordering.

Runs on the 8-virtual-device CPU mesh configured in conftest.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow


def _build(world_x, world_y, seed=11, **overrides):
    from avida_tpu.config import AvidaConfig
    from avida_tpu.core.state import init_population
    from avida_tpu.ops import birth as birth_ops
    from avida_tpu.world import World, default_ancestor

    cfg = AvidaConfig()
    cfg.WORLD_X = world_x
    cfg.WORLD_Y = world_y
    cfg.TPU_MAX_MEMORY = 200
    cfg.RANDOM_SEED = seed
    for k, v in overrides.items():
        cfg.set(k, v)
    w = World(cfg=cfg)
    st = init_population(w.params, default_ancestor(w.instset), jax.random.key(seed))
    neighbors = jnp.asarray(
        birth_ops.neighbor_table(world_x, world_y, cfg.WORLD_GEOMETRY))
    return w.params, st, neighbors


def _run_updates(params, st, neighbors, n_updates, seed=3):
    from avida_tpu.ops.update import update_step

    key = jax.random.key(seed)
    executed = []
    for u in range(n_updates):
        key, k = jax.random.split(key)
        st, ex = update_step(params, st, k, neighbors, jnp.int32(u))
    jax.block_until_ready(st)
    return st


def _state_arrays(st):
    return {name: np.asarray(getattr(st, name))
            for name in st.__dataclass_fields__}


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_matches_unsharded_bitexact():
    from avida_tpu.parallel import make_mesh, shard_neighbors, shard_population

    # 8x16 world: 16 rows over 8 devices = 2-row bands per device
    params, st0, neighbors = _build(8, 16)

    ref = _run_updates(params, st0, neighbors, 6)

    mesh = make_mesh(jax.devices()[:8])
    st_sh = shard_population(st0, mesh)
    nb_sh = shard_neighbors(neighbors, mesh)
    got = _run_updates(params, st_sh, nb_sh, 6)

    ref_a, got_a = _state_arrays(ref), _state_arrays(got)
    for name in ref_a:
        np.testing.assert_array_equal(
            ref_a[name], got_a[name],
            err_msg=f"sharded/unsharded mismatch in field {name}")

    # sanity: the run did something (organisms executed, and some divided)
    assert np.asarray(ref.insts_executed).sum() > 0


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_multi_deme_sharded_bitexact_with_boundary_births():
    """Deme-aligned sharding (BASELINE config 5): an 8-deme world sharded
    one deme per device, run long enough for births to occur, with deme
    migration ON so offspring actually cross shard boundaries, plus a
    CompeteDemes block replacement mid-run.  Sharded == unsharded
    bit-for-bit (the migration analogue of cMultiProcessWorld's
    deterministic migrant ordering, cMultiProcessWorld.cc:294-310)."""
    from avida_tpu.ops import demes as deme_ops
    from avida_tpu.ops.update import update_step
    from avida_tpu.parallel import (make_mesh, shard_neighbors,
                                    shard_population)

    # 8x16 world, 8 demes of 2 rows = one deme per device; fast updates
    params, st0, neighbors = _build(
        8, 16, NUM_DEMES=8, DEMES_MIGRATION_RATE=0.3,
        AVE_TIME_SLICE=100, TPU_MAX_STEPS_PER_UPDATE=100)

    def run(params, st, neighbors, n_updates):
        key = jax.random.key(3)
        pre_compete = None
        for u in range(n_updates):
            key, k = jax.random.split(key)
            st, _ = update_step(params, st, k, neighbors, jnp.int32(u))
            if u == 14:       # deme competition mid-run (block replacement)
                pre_compete = st.alive        # snapshot BEFORE replacement
                st = deme_ops.compete_demes(params, st, jax.random.key(99), 1)
        jax.block_until_ready(st)
        return st, pre_compete

    ref, ref_pre = run(params, st0, neighbors, 22)

    mesh = make_mesh(jax.devices()[:8])
    got, _ = run(params, shard_population(st0, mesh),
                 shard_neighbors(neighbors, mesh), 22)

    ref_a, got_a = _state_arrays(ref), _state_arrays(got)
    for name in ref_a:
        np.testing.assert_array_equal(
            ref_a[name], got_a[name],
            err_msg=f"sharded/unsharded mismatch in field {name}")

    # the run must actually have exercised cross-deme traffic: offspring
    # born outside the seed deme BEFORE the compete event replicated the
    # seed deme's block (only migration can put them there -- the compete
    # itself would make this assertion vacuous)
    cpd = params.num_cells // 8
    seed_deme = (params.num_cells // 2) // cpd
    alive_per_deme = np.asarray(ref_pre).reshape(8, cpd).sum(axis=1)
    others = [alive_per_deme[d] for d in range(8) if d != seed_deme]
    assert sum(others) > 0, (
        f"no birth ever crossed a deme/shard boundary: {alive_per_deme}")


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_shard_mapped_kernel_matches_unsharded():
    """The Pallas cycle kernel on a MULTI-DEVICE mesh: run_packed
    shard_maps the launch over the `cells` axis (one independent
    pallas_call per shard), while the birth flush stays on the GSPMD
    path.  The sharded trajectory -- including births from the seed cell,
    which sits exactly on the shard-0/shard-1 band boundary -- must match
    the unsharded kernel trajectory bit-for-bit.  Interpret mode on the
    virtual-device CPU mesh; the same shard_map wrapping runs natively
    on multi-chip TPU."""
    from avida_tpu.parallel import (make_mesh, shard_neighbors,
                                    shard_population)

    # 32x32 = 1024 cells: block 512 x 2 shards => the live band really
    # spans both shards (smaller worlds collapse into shard 0's band).
    # Mutation-free so the per-shard kernel PRNG seed bases cannot leak
    # into the comparison (interpret-mode streams are lane-indexed).
    overrides = dict(COPY_MUT_PROB=0.0, DIVIDE_INS_PROB=0.0,
                     DIVIDE_DEL_PROB=0.0, SLICING_METHOD=0,
                     AVE_TIME_SLICE=100, TPU_MAX_STEPS_PER_UPDATE=100,
                     TPU_USE_PALLAS=1)
    params1, st0, neighbors = _build(32, 32, TPU_KERNEL_SHARDS=1,
                                     **overrides)
    params2, st0b, _ = _build(32, 32, TPU_KERNEL_SHARDS=2, **overrides)

    n_updates = 6            # first divide ~update 4; births cross bands
    ref = _run_updates(params1, st0, neighbors, n_updates)

    mesh = make_mesh(jax.devices()[:2])
    got = _run_updates(params2, shard_population(st0b, mesh),
                       shard_neighbors(neighbors, mesh), n_updates)

    ref_a, got_a = _state_arrays(ref), _state_arrays(got)
    for name in ref_a:
        np.testing.assert_array_equal(
            ref_a[name], got_a[name],
            err_msg=f"kernel sharded/unsharded mismatch in field {name}")
    # the run exercised the claim: an offspring was actually born
    assert np.asarray(ref.alive).sum() > 1, "no birth -- lengthen the run"


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_state_is_actually_distributed():
    from avida_tpu.parallel import make_mesh, shard_population

    params, st0, _ = _build(8, 16)
    mesh = make_mesh(jax.devices()[:8])
    st_sh = shard_population(st0, mesh)
    # the tape's cell axis must be partitioned across all 8 devices
    assert len(st_sh.tape.sharding.device_set) == 8
