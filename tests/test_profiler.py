"""Device performance attribution plane (observability/profiler.py).

Contract under test, layer by layer:

  * off is FREE: no profiler object, no perf.jsonl, no avida_perf_*
    families -- exporter output byte-compatible with the pre-plane
    repo, module counters untouched;
  * armed is INVISIBLE to physics: the evolved trajectory is
    bit-identical with profiling on or off (probes run staged phases
    on device-owned COPIES), and the traced update_step jaxpr digest
    is unchanged with TPU_PROFILE=1 in the environment (subprocess
    scripts/check_jaxpr.py -- the plane must never touch the program);
  * armed solo end-to-end: avida_perf_* families land in metrics.prom,
    {"record":"perf"} probe records in perf.jsonl, a perf block in
    --status, and the state footprint's padded bytes equal nbytes
    ground truth per leaf;
  * cached == fresh: a program loaded from the persistent compile
    cache reports cost/memory numbers EQUAL to the fresh compile that
    stored them (the manifest `perf` block);
  * multiworld armed: per-world footprint families on the batched
    path;
  * perf_tool: report renders, diff --gate passes identical artifacts,
    fails an injected regression with exit 4, and refuses a
    provenance mismatch with exit 3;
  * campaign: one `--arms headline` artifact end-to-end on CPU (slow);
  * the <2% recurring-overhead acceptance gauge via bench's
    prof_overhead_fields (slow).

Armed tests opt back IN via config overrides (tests/conftest.py pins
the env half to 0 suite-wide for hermeticity), and every test resets
the plane's process-level module state around itself.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from functools import partial

import numpy as np
import pytest

from avida_tpu.observability import profiler
from avida_tpu.utils import compilecache as cc
from avida_tpu.world import World

REPO = os.path.join(os.path.dirname(__file__), "..")
U = 12
ARMED = (("TPU_PROFILE", 1), ("TPU_PROFILE_EVERY", 2))


@pytest.fixture(autouse=True)
def _clean_plane():
    """The plane's report is process-level (like compilecache): reset
    around every test so an armed test's programs/footprint never leak
    into another test's exporter output."""
    profiler.reset_for_tests()
    yield
    profiler.reset_for_tests()


def _world(data_dir, seed=11, extra=()):
    ov = [("WORLD_X", 8), ("WORLD_Y", 8), ("RANDOM_SEED", seed),
          ("TPU_SYSTEMATICS", 0), ("TPU_MAX_STRETCH", 4),
          ("TPU_METRICS", 1)] + list(extra)
    return World(overrides=ov, data_dir=str(data_dir))


def _run(data_dir, seed=11, extra=()):
    w = _world(data_dir, seed, extra)
    w.run(max_updates=U)
    return w


# ---------------------------------------------------------------------------
# off: byte-compatible and free
# ---------------------------------------------------------------------------

def test_off_is_byte_compatible_and_zero_cost(tmp_path):
    w = _run(tmp_path / "off")
    assert w.profiler is None
    prom = (tmp_path / "off" / "metrics.prom").read_text()
    assert "avida_perf" not in prom
    assert not (tmp_path / "off" / profiler.PERF_FILE).exists()
    assert profiler.prom_families() == []
    assert all(v == 0 for v in profiler.counters().values())


def test_arming_is_config_or_env(monkeypatch):
    class Cfg(dict):
        def get(self, n, d=None):
            return super().get(n, d)
    assert not profiler.enabled(Cfg())          # conftest pins env to 0
    assert profiler.enabled(Cfg(TPU_PROFILE=1))
    monkeypatch.setenv("TPU_PROFILE", "1")
    assert profiler.enabled(Cfg())
    # cadence is an operator knob: env wins over config
    monkeypatch.setenv("TPU_PROFILE_EVERY", "5")
    assert profiler.probe_every(Cfg(TPU_PROFILE_EVERY=99)) == 5
    monkeypatch.delenv("TPU_PROFILE_EVERY")
    assert profiler.probe_every(Cfg(TPU_PROFILE_EVERY=99)) == 99


# ---------------------------------------------------------------------------
# armed: invisible to physics
# ---------------------------------------------------------------------------

def test_trajectory_bit_identical_on_or_off(tmp_path):
    w_off = _run(tmp_path / "a")
    profiler.reset_for_tests()
    w_on = _run(tmp_path / "b", extra=ARMED)
    assert w_on.profiler is not None
    for fname in w_off.state.__dataclass_fields__:
        va = getattr(w_off.state, fname)
        if va is None:
            continue
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(getattr(w_on.state, fname)),
            err_msg=f"field {fname} diverged under TPU_PROFILE=1")
    assert int(np.asarray(w_off._total_births)) \
        == int(np.asarray(w_on._total_births))


def test_jaxpr_digest_unchanged_when_armed():
    """TPU_PROFILE=1 in the ENVIRONMENT must not perturb the traced
    update program (the plane hooks chunk boundaries and copies, never
    the jaxpr).  Subprocess: the snapshot gate under an armed env."""
    env = dict(os.environ)
    env["TPU_PROFILE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "check_jaxpr.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# armed solo end-to-end
# ---------------------------------------------------------------------------

def test_armed_solo_end_to_end(tmp_path):
    w = _run(tmp_path / "on", extra=ARMED)
    prom = (tmp_path / "on" / "metrics.prom").read_text()
    for fam in ("avida_perf_chunks_total 3", "avida_perf_updates_total 12",
                "avida_perf_probes_total 2", "avida_perf_chunk_wall_ms",
                "avida_perf_phase_ms{phase=", "avida_perf_state_bytes",
                "avida_perf_state_leaf_bytes{leaf=\"genome\"}",
                "avida_perf_programs_total"):
        assert fam in prom, f"{fam} missing from metrics.prom"

    # perf.jsonl: probe records at chunks 1 and 3 (EVERY=2) + final
    recs = profiler.read_perf_records(str(tmp_path / "on"))
    assert len(recs) == 3
    assert [r["final"] for r in recs] == [False, False, True]
    assert recs[-1]["update"] == U
    assert all(r["record"] == "perf" and r["kind"] == "solo"
               for r in recs)
    assert recs[-1]["programs"] >= 1       # AOT capture, cache disabled

    # --status block renders from the published families
    from avida_tpu.observability.exporter import format_status, read_metrics
    status = format_status(read_metrics(
        str(tmp_path / "on" / "metrics.prom")))
    assert "perf " in status and "probes" in status

    # footprint: padded bytes are nbytes ground truth, leaf by leaf
    fp = profiler.state_footprint(w.state)
    for name, leaf in fp["leaves"].items():
        arr = getattr(w.state, name)
        assert leaf["bytes"] == np.asarray(arr).nbytes, name
    assert fp["total_bytes"] == sum(lf["bytes"]
                                    for lf in fp["leaves"].values())
    assert 0.0 < fp["alive_frac"] <= 1.0
    assert recs[-1]["state_bytes"] == fp["total_bytes"]


# ---------------------------------------------------------------------------
# cached == fresh (the compile-cache manifest leg)
# ---------------------------------------------------------------------------

def _toy():
    import jax

    @partial(jax.jit, static_argnums=(0, 2), donate_argnums=(1,))
    def toy(scale, x, steps, y):
        def body(c, _):
            c = c * scale + y
            return c, c.sum()
        return jax.lax.scan(body, x, None, length=steps)
    return toy


def _toy_args():
    import jax.numpy as jnp
    x = jnp.arange(8, dtype=jnp.float32)
    y = jnp.full((8,), 0.5, jnp.float32)
    return (3, x, 4, y)


def test_program_report_cached_equals_fresh(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_COMPILE_CACHE", "1")
    monkeypatch.setenv("TPU_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    monkeypatch.setenv("TPU_PROFILE", "1")
    cc.reset_for_tests()
    try:
        cc.call(_toy(), "toy", _toy_args())
        fresh = profiler.program_reports()
        assert len(fresh) == 1
        (key, rep), = fresh.items()
        assert rep["source"] == "compile"
        assert rep["cost"].get("flops", 0) >= 0

        # simulated fresh process: disk load must report EQUAL numbers
        cc.reset_for_tests()
        profiler.reset_for_tests()
        cc.call(_toy(), "toy", _toy_args())
        assert cc.cache_load_count() == 1
        cached = profiler.program_reports()
        assert set(cached) == {key}
        assert cached[key]["source"] == "cache_load"
        assert cached[key]["cost"] == fresh[key]["cost"]
        assert cached[key]["memory"] == fresh[key]["memory"]
    finally:
        cc.reset_for_tests()


def test_aot_capture_when_cache_disabled(monkeypatch):
    """Cache off + plane armed: the plain-jit path takes the AOT
    flavor so cost capture still happens, bit-exact by construction."""
    monkeypatch.setenv("TPU_PROFILE", "1")
    out, sums = cc.call(_toy(), "toy", _toy_args())
    reps = profiler.program_reports()
    assert len(reps) == 1
    assert next(iter(reps.values()))["source"] == "aot"
    out2, _ = _toy()(*_toy_args())
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


# ---------------------------------------------------------------------------
# multiworld armed
# ---------------------------------------------------------------------------

def test_multiworld_armed_per_world_footprint(tmp_path):
    from avida_tpu.parallel.multiworld import MultiWorld
    mw = MultiWorld.from_seeds([11, 12], overrides=list(ARMED) + [
        ("WORLD_X", 8), ("WORLD_Y", 8), ("TPU_SYSTEMATICS", 0),
        ("TPU_MAX_STRETCH", 4), ("TPU_METRICS", 1)],
        data_dir=str(tmp_path))
    mw.run(max_updates=8)
    prom = (tmp_path / "metrics.prom").read_text()
    assert "avida_perf_chunks_total" in prom
    assert "avida_perf_world_state_bytes" in prom
    recs = profiler.read_perf_records(str(tmp_path))
    assert recs and recs[-1]["kind"] == "multiworld"
    assert recs[-1]["per_world_bytes"] * 2 == recs[-1]["state_bytes"]
    # the batched probe attributes the world-folded stages
    assert set(recs[-1]["phases"]) <= {"pre", "cycles", "post"}


# ---------------------------------------------------------------------------
# perf_tool: report / diff / campaign
# ---------------------------------------------------------------------------

_PROV = {"schema": "avida-bench-v1", "platform": "cpu",
         "device_kind": "cpu", "device_count": 1, "x64": False,
         "code": "abc123", "jax": "0", "jaxlib": "0", "env": {}}


def _artifact(tmp_path, name, value, pack_ms, prov=None):
    line = {"metric": "org_instructions_per_sec", "value": value,
            "unit": "inst/s", "pack_ms": pack_ms,
            "provenance": prov or _PROV}
    p = tmp_path / name
    p.write_text(json.dumps(line))
    return str(p)


def _perf_tool(*args):
    return subprocess.run(
        [sys.executable, os.path.join("scripts", "perf_tool.py")]
        + list(args), cwd=REPO, capture_output=True, text=True,
        timeout=600)


def test_perf_tool_diff_gate(tmp_path):
    a = _artifact(tmp_path, "a.json", 1000.0, 5.0)
    same = _artifact(tmp_path, "same.json", 1020.0, 5.1)
    slow = _artifact(tmp_path, "slow.json", 850.0, 5.0)
    other = _artifact(tmp_path, "other.json", 1000.0, 5.0,
                      prov=dict(_PROV, code="zzz"))
    assert _perf_tool("diff", a, same, "--gate").returncode == 0
    p = _perf_tool("diff", a, slow, "--gate")
    assert p.returncode == 4 and "REGRESSION" in p.stdout
    # without --gate the regression is advisory (exit 0)
    assert _perf_tool("diff", a, slow).returncode == 0
    # provenance mismatch refuses loudly; --force compares anyway
    p = _perf_tool("diff", a, other, "--gate")
    assert p.returncode == 3 and "apples-to-oranges" in p.stderr
    assert _perf_tool("diff", a, other, "--gate",
                      "--force").returncode == 0
    # lower-better direction: a *_ms field growing past tol regresses
    slow_ms = _artifact(tmp_path, "slowms.json", 1000.0, 7.0)
    assert _perf_tool("diff", a, slow_ms, "--gate").returncode == 4


def test_perf_tool_report(tmp_path):
    _run(tmp_path / "on", extra=ARMED)
    p = _perf_tool("report", str(tmp_path / "on"))
    assert p.returncode == 0, p.stderr
    out = p.stdout
    assert "fenced probes" in out and "phases (last probe)" in out
    assert "probe timeline" in out and "state " in out
    # an unarmed dir reports the arming hint instead
    p = _perf_tool("report", str(tmp_path))
    assert p.returncode == 1 and "TPU_PROFILE=1" in p.stdout


def test_bench_provenance_strict_fields():
    prov = profiler.bench_provenance(run_time=123.0)
    for f in profiler.PROVENANCE_STRICT:
        assert f in prov, f
    assert prov["schema"] == profiler.PROVENANCE_SCHEMA
    assert prov["code"] == cc.code_digest()
    assert prov["generated_at"] == 123.0
    assert profiler.provenance_mismatches(prov, dict(prov)) == []
    assert profiler.provenance_mismatches(prov, {}) \
        == [("provenance", "present", "absent")]


@pytest.mark.slow
def test_campaign_end_to_end(tmp_path):
    """One `perf_tool campaign --arms headline` artifact on CPU: the
    merged self-describing JSON a regression gate can diff against."""
    env = dict(os.environ)
    env["BENCH_PHASES"] = "0"            # headline only, no staged rows
    out = str(tmp_path / "bench.json")
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "perf_tool.py"),
         "campaign", "--arms", "headline", "--side", "16",
         "--out", out], cwd=REPO, env=env, capture_output=True,
        text=True, timeout=1200)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(open(out).read())
    assert doc["artifact"] == "avida-bench-campaign-v1"
    assert doc["arms"]["headline"]["value"] > 0
    for f in profiler.PROVENANCE_STRICT:
        assert f in doc["provenance"]
    # a campaign artifact diffs against itself cleanly, gated
    assert _perf_tool("diff", out, out, "--gate").returncode == 0


@pytest.mark.slow
def test_prof_overhead_under_two_percent():
    """The acceptance gauge: the plane's recurring per-chunk hook cost
    stays under 2% of the plain chunk wall (bench.prof_overhead_fields
    -- direct fenced attribution, BASELINE.md measurement rules)."""
    sys.path.insert(0, REPO)
    import bench
    fields = bench.prof_overhead_fields(16, updates=16)
    assert fields["prof_overhead_pct"] < 2.0, fields
    assert fields["prof_probe_ms"] > 0.0
