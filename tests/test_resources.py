"""Resource dynamics tests (ops/resources.py).

Scenario model: the reference's resources_9r consistency test (logic-9 with
nine depletable pools) and spatial_res_100u (diffusing grid resource).
"""

import jax.numpy as jnp
import numpy as np

from avida_tpu.config import AvidaConfig, default_instset
from avida_tpu.config.environment import (Environment, Process, Reaction,
                                          Requisite, Resource, PROCTYPE_POW,
                                          load_environment)
from avida_tpu.core.state import make_world_params
from avida_tpu.ops import resources as res_ops
from avida_tpu.ops import tasks as tasks_ops


def limited_env():
    """logic-9-style environment where NOT draws from a finite pool."""
    env = Environment()
    env.resources.append(Resource("resNOT", inflow=100.0, outflow=0.01,
                                  initial=1000.0))
    env.reactions.append(Reaction(
        "NOT", "not",
        [Process(value=1.0, type=PROCTYPE_POW, resource="resNOT",
                 max_number=5.0, max_fraction=0.5)],
        [Requisite(max_task_count=1)]))
    return env


def make_params(env, nx=4, ny=4):
    cfg = AvidaConfig()
    cfg.WORLD_X = nx
    cfg.WORLD_Y = ny
    cfg.TPU_MAX_MEMORY = 64
    return make_world_params(cfg, default_instset(), env)


def test_global_inflow_outflow():
    params = make_params(limited_env())
    r = jnp.asarray([1000.0])
    r = res_ops.step_global(params, r)
    # 1000 + 100 - 0.01*1000 = 1090
    assert float(r[0]) == 1090.0


def test_consume_scaling_under_contention():
    env = limited_env()
    params = make_params(env)
    tables = tasks_ops.env_tables_to_device(params)
    n = params.num_cells
    rewarded = jnp.ones((n, 1), bool)          # every organism fires NOT
    resources = jnp.asarray([10.0])            # not enough for 16 x 5
    amount, resources, _ = res_ops.consume(
        params, tables, rewarded, 1.0, resources, jnp.zeros((0, n)))
    # each wants min(10*0.5, 5) = 5, total demand 80 > 10 -> scaled to 10/80
    np.testing.assert_allclose(np.asarray(amount[:, 0]), 5 * 10 / 80, rtol=1e-5)
    assert float(resources[0]) < 1e-4          # pool drained


def test_infinite_resource_amount_is_max():
    env = limited_env()
    env.reactions[0].processes[0].resource = None
    params = make_params(env)
    tables = tasks_ops.env_tables_to_device(params)
    n = params.num_cells
    rewarded = jnp.zeros((n, 1), bool).at[3, 0].set(True)
    amount, resources, _ = res_ops.consume(
        params, tables, rewarded, 1.0, jnp.zeros(1), jnp.zeros((0, n)))
    assert float(amount[3, 0]) == 5.0
    assert float(amount[0, 0]) == 0.0


def test_spatial_diffusion_spreads_and_conserves():
    # reference-default diffusion rates (1.0) must be numerically stable
    env = Environment()
    env.resources.append(Resource("food", geometry="torus", inflow=0.0,
                                  outflow=0.0, xdiffuse=1.0, ydiffuse=1.0))
    params = make_params(env, nx=8, ny=8)
    g = jnp.zeros((1, 64)).at[0, 0].set(64.0)   # point mass at cell 0
    total0 = float(g.sum())
    for _ in range(20):
        g = res_ops.step_spatial(params, g)
    assert abs(float(g.sum()) - total0) < 1e-3, "diffusion must conserve mass"
    spread = (np.asarray(g[0]) > 0.1).sum()
    assert spread > 30, f"mass should spread, only {spread} cells touched"
    assert float(g[0, 0]) < 10.0


def test_reaction_reward_uses_consumed_amount():
    env = limited_env()
    params = make_params(env)
    tables = tasks_ops.env_tables_to_device(params)
    n = params.num_cells
    # one org performs NOT with ample resource: amount = min(1000*0.5, 5) = 5
    # -> bonus *= 2^(value*amount) = 2^5
    logic_id = jnp.full(n, -1, jnp.int32).at[0].set(15)   # a NOT id
    io = jnp.zeros(n, bool).at[0].set(True)
    bonus0 = jnp.ones(n, jnp.float32)
    tc = jnp.zeros((n, 1), jnp.int32)
    rc = jnp.zeros((n, 1), jnp.int32)
    bonus, tc, rc, resources, _, _, _ = tasks_ops.apply_reactions(
        params, tables, io, logic_id, bonus0, tc, rc,
        jnp.asarray([1000.0]), jnp.zeros((0, n)))
    assert float(bonus[0]) == 32.0
    assert float(bonus[1]) == 1.0
    assert float(resources[0]) == 995.0
    assert int(tc[0, 0]) == 1 and int(rc[0, 0]) == 1


def test_environment_cfg_resource_parsing(tmp_path):
    p = tmp_path / "environment.cfg"
    p.write_text(
        "RESOURCE glucose:inflow=10:outflow=0.05:initial=50\n"
        "RESOURCE grid_food:geometry=torus:xdiffuse=0.3:inflowx1=0:"
        "inflowx2=3:inflowy1=0:inflowy2=3:inflow=1\n"
        "REACTION NOT not process:value=1.0:type=pow:resource=glucose:"
        "max=2:frac=0.25 requisite:max_count=1\n")
    env = load_environment(str(p))
    assert len(env.global_resources()) == 1
    assert len(env.spatial_resources()) == 1
    assert env.spatial_resources()[0].xdiffuse == 0.3
    t = env.device_tables()
    assert t["proc_res_idx"][0] == 0
    assert not t["proc_res_spatial"][0]
    assert t["proc_max"][0] == 2.0
    assert t["proc_frac"][0] == 0.25


def test_world_run_with_limited_resource():
    """End-to-end: a world whose only reward is resource-bound still runs,
    and the pool converges toward inflow/outflow equilibrium."""
    from avida_tpu.world import World
    cfg = AvidaConfig()
    cfg.WORLD_X = 6
    cfg.WORLD_Y = 6
    cfg.RANDOM_SEED = 5
    cfg.TPU_MAX_MEMORY = 256
    w = World(cfg=cfg)
    w.environment = limited_env()
    from avida_tpu.core.state import make_world_params
    w.params = make_world_params(w.cfg, w.instset, w.environment)
    w.inject()
    for _ in range(25):
        w.run_update()
        w.update += 1
    assert w.num_organisms >= 1
    lvl = float(np.asarray(w.state.resources)[0])
    assert 0.0 < lvl < 12000.0
