"""Offspring reversion/sterilization via the batched Test CPU.

Reference: cHardwareBase::Divide_TestFitnessMeasures (cc:866): offspring
sandbox fitness classifies fatal/detrimental/neutral/beneficial vs the
parent's cached test fitness (Systematics::GenomeTestMetrics), then
REVERT_*/STERILIZE_* probabilities apply.
"""

from __future__ import annotations

import numpy as np

from avida_tpu.config import AvidaConfig
from avida_tpu.world import World


def _world(**kw):
    cfg = AvidaConfig()
    cfg.WORLD_X = 10
    cfg.WORLD_Y = 10
    cfg.TPU_MAX_MEMORY = 320
    cfg.RANDOM_SEED = 9
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    cfg.COPY_MUT_PROB = 0.02          # plenty of deleterious mutants
    cfg.set("TPU_SYSTEMATICS", 0)
    for k, v in kw.items():
        cfg.set(k, v)
    return World(cfg=cfg)


def test_revert_fatal_keeps_population_breed_true():
    """With REVERT_FATAL=1, every inviable offspring is replaced by its
    parent's genome, so all newborns carry sandbox-viable genomes."""
    w = _world(REVERT_FATAL=1.0)
    assert w._revert_on
    w.inject()
    w.run(max_updates=40)
    assert w.num_organisms > 2
    # the genotype test cache filled up (GenomeTestMetrics at work)
    assert len(w.test_metrics) > 0
    # every living organism's genome is sandbox-viable: fatal offspring
    # were reverted to their parent genome at birth
    st = w.state
    alive = np.nonzero(np.asarray(st.alive))[0]
    fits = w.test_metrics.get_fitness(np.asarray(st.genome)[alive],
                                      np.asarray(st.genome_len)[alive])
    assert (fits > 0).all(), f"{(fits == 0).sum()} inviable organisms survived"


def test_sterilize_fatal_makes_inviable_newborns_infertile():
    """Reference semantics: sterilized offspring live (occupying cells)
    but can never divide."""
    w = _world(STERILIZE_FATAL=1.0)
    w.inject()
    w.run(max_updates=40)
    st = w.state
    alive = np.nonzero(np.asarray(st.alive))[0]
    assert len(alive) > 1
    fits = w.test_metrics.get_fitness(np.asarray(st.genome)[alive],
                                      np.asarray(st.genome_len)[alive])
    sterile = np.asarray(st.sterile)[alive]
    divides = np.asarray(st.num_divides)[alive]
    # every inviable organism in the population was sterilized at birth
    # and has never divided
    inviable = fits == 0
    assert sterile[inviable].all(), "inviable newborn escaped sterilization"
    assert (divides[sterile] == 0).all(), "a sterile organism divided"
    assert sterile.any(), "mutation rate should have produced sterile cases"


def test_reversion_off_lets_inviable_genomes_in():
    """Control: with reversion off at the same mutation rate, inviable
    genomes DO accumulate -- proving the mechanism above does the work."""
    w = _world()
    assert not w._revert_on
    w.inject()
    w.run(max_updates=40)
    from avida_tpu.systematics.test_metrics import GenomeTestMetrics
    tm = GenomeTestMetrics(w.params)
    st = w.state
    alive = np.nonzero(np.asarray(st.alive))[0]
    fits = tm.get_fitness(np.asarray(st.genome)[alive],
                          np.asarray(st.genome_len)[alive])
    assert (fits == 0).any(), "expected some inviable genomes without reversion"
