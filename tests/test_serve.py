"""Streaming serve layer, host-only tier (service/serve.py + the fleet
integration + utils/churntrace.py + fleet_tool flags).

Everything here runs on a fake clock with SCRIPTED children -- the
serve-class child is emulated at the PROTOCOL level (control.json in,
serve.json + heartbeat out) through the Supervisor._spawn seam, so no
test compiles a world.  The jax side of the same contract (ghost
identity, rider promotion without a recompile, demotion checkpoints)
lives in tests/test_serve_batch.py."""

from __future__ import annotations

import json
import os
import sys

import pytest

import test_supervisor as ts
from avida_tpu.observability.exporter import read_metrics
from avida_tpu.observability.runlog import read_records
from avida_tpu.service.fleet import (JOURNAL_FILE, FleetConfig,
                                     FleetOrchestrator)
from avida_tpu.service.serve import (SpecArgv, batch_ineligible_reason,
                                     member_argv, static_signature,
                                     width_class)
from avida_tpu.utils import churntrace

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "scripts"))
import fleet_tool  # noqa: E402

SUP_ENV = {"TPU_WATCHDOG_SEC": "10", "TPU_SUPERVISE_POLL_SEC": "0.5",
           "TPU_SUPERVISE_GRACE_SEC": "30",
           "TPU_SUPERVISE_MAX_RETRIES": "2",
           "TPU_SUPERVISE_BACKOFF_BASE": "0.1",
           "TPU_SUPERVISE_BACKOFF_CAP": "0.5",
           "TPU_SUPERVISE_HEALTHY_SEC": "1000000000"}

ARGS = ["-u", "40", "-set", "WORLD_X", "8", "-set", "WORLD_Y", "8"]


# ---------------------------------------------------------------------------
# the signature / width-class / eligibility units
# ---------------------------------------------------------------------------

def test_spec_argv_parsing():
    pa = SpecArgv(["-s", "7", "-set", "RANDOM_SEED", "9", "-u", "50",
                   "-d", "out", "-c", "cfgdir", "-v"])
    assert pa.effective_seed == 7          # -s beats -set RANDOM_SEED
    assert pa.max_updates == 50
    assert pa.data_dir == "out"
    assert pa.config_dir == "cfgdir"
    assert pa.residual == ["-v"]
    assert SpecArgv(["-set", "RANDOM_SEED", "9"]).effective_seed == 9
    assert SpecArgv(["-u", "10"]).effective_seed is None


def test_width_class_pow2_set():
    assert [width_class(n, 2, 16) for n in (1, 2, 3, 4, 5, 9, 17, 100)] \
        == [2, 2, 4, 4, 8, 16, 16, 16]
    assert width_class(1, 4, 16) == 4      # min width floors
    assert width_class(3, 2, 6) == 4       # cap rounds DOWN to pow2


def test_signature_resolves_config_dir_contents(tmp_path):
    """Two config dirs with identical contents coalesce; editing a
    config file splits the class even when argv is unchanged."""
    d1, d2 = tmp_path / "c1", tmp_path / "c2"
    for d in (d1, d2):
        os.makedirs(d)
        with open(d / "avida.cfg", "w") as f:
            f.write("WORLD_X 8\nWORLD_Y 8\n")
    s1 = static_signature({"argv": ["-c", str(d1), "-s", "1"]})
    s2 = static_signature({"argv": ["-c", str(d2), "-s", "2"]})
    assert s1 == s2
    with open(d2 / "avida.cfg", "a") as f:
        f.write("COPY_MUT_PROB 0.01\n")
    assert static_signature({"argv": ["-c", str(d2), "-s", "2"]}) != s1


def test_member_argv_strips_routing_keeps_statics():
    spec = {"argv": ["-s", "3", "-d", "out", "-set", "TPU_CKPT_DIR",
                     "ck", "-set", "WORLD_X", "8", "-u", "40"]}
    assert member_argv(spec) == ["-set", "WORLD_X", "8", "-u", "40"]


def test_batch_ineligible_reasons():
    assert batch_ineligible_reason({"argv": ARGS}) is None
    assert "solo" in batch_ineligible_reason(
        {"argv": ARGS + ["--telemetry"]})
    assert "solo" in batch_ineligible_reason(
        {"argv": ARGS + ["-set", "TPU_TRACE", "1"]})
    assert "per-process" in batch_ineligible_reason(
        {"argv": ARGS + ["-set", "TPU_FAULT", "crash"]})
    assert batch_ineligible_reason(
        {"argv": ARGS + ["-set", "TPU_TRACE", "0"]}) is None


# ---------------------------------------------------------------------------
# churn traces (the gen-trace satellite)
# ---------------------------------------------------------------------------

def test_churntrace_grammar_and_determinism(tmp_path):
    evs = churntrace.generate(7, jobs=6, classes=2, cancel_frac=0.34,
                              span=20, updates=30)
    text = churntrace.format_trace(evs, seed=7)
    assert text == churntrace.format_trace(
        churntrace.generate(7, jobs=6, classes=2, cancel_frac=0.34,
                            span=20, updates=30), seed=7)
    path = tmp_path / "t.trace"
    path.write_text(text)
    parsed = churntrace.parse_trace(str(path))
    assert [e.text for e in parsed] == [e.text for e in evs]
    assert {e.kind for e in parsed} == {"submit", "cancel"}
    # times are sorted, cancels follow their submit
    assert [e.t for e in parsed] == sorted(e.t for e in parsed)
    for bad in ("submit:job=a,seed=1,u=5", "nope:job=a@t=1",
                "submit:seed=1,u=5@t=1", "submit:job=a,bare@t=1",
                "submit:job=a,seed=x,u=5@t=1"):
        with pytest.raises(ValueError):
            churntrace.parse_event(bad)


def test_churntrace_replay_drives_spool(tmp_path):
    spool = str(tmp_path / "spool")
    clk = ts.FakeClock()
    evs = churntrace.parse_trace([
        "submit:job=a,seed=1,u=5@t=0",
        "submit:job=b,seed=2,u=5,tenant=org1@t=1",
        "cancel:job=a@t=2",
    ])
    seen = []
    churntrace.replay(spool, evs, lambda e: ARGS + ["-s",
                                                    e.args["seed"]],
                      clock=clk, sleep=clk.sleep,
                      on_event=lambda e: seen.append(e.kind))
    assert seen == ["submit", "submit", "cancel"]
    assert os.path.exists(os.path.join(spool, "a.json"))
    assert os.path.exists(os.path.join(spool, "a.cancel"))
    spec_b = json.load(open(os.path.join(spool, "b.json")))
    assert spec_b["tenant"] == "org1" and spec_b["batch"] is True


def test_fleet_tool_gen_trace_cli(tmp_path):
    out = str(tmp_path / "x.trace")
    assert fleet_tool.main(["gen-trace", out, "--seed", "5",
                            "--jobs", "4", "--classes", "2"]) == 0
    evs = churntrace.parse_trace(out)
    assert sum(1 for e in evs if e.kind == "submit") == 4
    assert fleet_tool.main(["gen-trace", str(tmp_path / "y")]) == 2


def test_fleet_tool_shard_and_backpressure(tmp_path):
    spool = str(tmp_path / "spool")
    p1 = fleet_tool.submit(spool, "s1", ARGS, shard=4)
    p2 = fleet_tool.submit(spool, "s2", ARGS, shard=4)
    assert "/shard-" in p1 and "/shard-" in p2
    # duplicate detection reaches across shards
    with pytest.raises(ValueError, match="already exists"):
        fleet_tool.submit(spool, "s1", ARGS, shard=4)
    with pytest.raises(fleet_tool.QueueFullError):
        fleet_tool.submit(spool, "s3", ARGS, backpressure=2)
    # CLI exit code 3 for the held submit
    assert fleet_tool.main(["submit", spool, "s3", "--backpressure",
                            "2", "--", "-u", "1"]) == 3
    assert fleet_tool.submit(spool, "s3", ARGS, backpressure=5)


# ---------------------------------------------------------------------------
# the serve pool against protocol-level stub children
# ---------------------------------------------------------------------------

class StubServeProc(ts.FakeProc):
    """A --serve-worlds child emulated at the protocol level: admits
    members from control.json at every poll, advances them `rate`
    updates per fake second, retires them at their max_updates (or on
    demotion), reports through serve.json, keeps the supervisor
    heartbeat fresh, and exits on shutdown."""

    def __init__(self, clock, rate=10.0, crash_after=None):
        super().__init__(clock, code=0, runtime=None)
        self.rate = rate
        self.crash_after = crash_after  # fake seconds -> exit 1
        self.members: dict = {}
        self.finished: dict = {}
        self._last_t = None

    def _spawned(self, argv, env, logf):
        super()._spawned(argv, env, logf)
        i = argv.index("--serve-worlds")
        self.control = argv[i + 1]
        self.data = argv[argv.index("-d") + 1]
        self._last_t = self.clock()

    def poll(self):
        if self.returncode is not None:
            return self.returncode
        now = self.clock()
        dt, self._last_t = now - self._last_t, now
        if self.crash_after is not None \
                and now - self.t0 >= self.crash_after:
            self.returncode = 1
            return self.returncode
        try:
            with open(self.control) as f:
                ctl = json.load(f)
        except (OSError, ValueError):
            ctl = {}
        width = int(ctl.get("width", 2))
        want = {e["name"]: e for e in ctl.get("members") or []}
        for n in list(self.members):
            if n not in want:               # demotion
                self.finished[n] = {
                    "state": "retired",
                    "update": int(self.members.pop(n)["u"])}
        for n, e in want.items():
            if n not in self.members and n not in self.finished \
                    and len(self.members) < width:
                self.members[n] = {"u": 0.0, "entry": e}
        for n in list(self.finished):
            if n not in want:               # ack consumed
                del self.finished[n]
        for n, m in list(self.members.items()):
            m["u"] += self.rate * dt
            cap = m["entry"].get("max_updates")
            if cap is not None and m["u"] >= cap:
                self.finished[n] = {"state": "done", "update": int(cap)}
                del self.members[n]
        status = {
            "width": width, "live": len(self.members),
            "ghosts": width - len(self.members), "compiles": 3,
            "members": {n: {"state": "live", "update": int(m["u"])}
                        for n, m in self.members.items()},
            "finished": dict(self.finished),
        }
        os.makedirs(self.data, exist_ok=True)
        with open(os.path.join(self.data, "serve.json"), "w") as f:
            json.dump(status, f)
        ts._write_metrics(self.data, hb=now)
        if ctl.get("shutdown") and not self.members:
            self.returncode = 0
        return self.returncode

    def terminate(self):
        if self.returncode is None:
            ts._write_metrics(self.data, hb=self.clock(), preempted=1)
            self.returncode = 0


class ServeStubs:
    """spawn_factory: serve-class leaders get StubServeProc, plain jobs
    get the scripted FakeProc from `scripts` (test_fleet pattern)."""

    def __init__(self, clock, scripts=None, serve_kw=None):
        self.clock = clock
        self.scripts = {k: list(v) for k, v in (scripts or {}).items()}
        self.serve_kw = list(serve_kw or [])
        self.spawned = []

    def factory(self, job):
        def spawn(argv, env, logf):
            if "--serve-worlds" in argv:
                kw = self.serve_kw.pop(0) if self.serve_kw else {}
                proc = StubServeProc(self.clock, **kw)
            else:
                proc = self.scripts[job.name].pop(0)()
            proc._spawned(argv, env, logf)
            if not isinstance(proc, StubServeProc) and "-d" in argv:
                proc._data = argv[argv.index("-d") + 1]
            self.spawned.append((job.name, proc, argv))
            return proc
        return spawn


def _cfg(**kw):
    base = dict(max_jobs=2, poll_sec=0.5, breaker_k=3, breaker_sec=60.0,
                drain_sec=30.0, dynamic=True)
    base.update(kw)
    return FleetConfig(**base)


def _mk_fleet(tmp_path, clk, scripts=None, serve_kw=None, **cfg_kw):
    spool = str(tmp_path / "spool")
    stubs = ServeStubs(clk, scripts, serve_kw)
    fleet = FleetOrchestrator(spool, cfg=_cfg(**cfg_kw),
                              env=dict(SUP_ENV), clock=clk,
                              sleep=clk.sleep,
                              spawn_factory=stubs.factory)
    return fleet, spool, stubs


def _drive(fleet, clk, max_ticks=400):
    for _ in range(max_ticks):
        if not fleet.poll_once():
            return
        clk.sleep(0.5)
    raise AssertionError("fleet did not drain within the tick budget")


def _events(spool):
    recs = [r for r in read_records(os.path.join(spool, JOURNAL_FILE))
            if r.get("record") == "fleet"]
    return [(r["event"], r.get("job")) for r in recs], recs


def test_serve_pool_hit_miss_done_and_gauges(tmp_path):
    """Three same-class arrivals spawn ONE warm child (cache miss); a
    late rider routes into its free ghost slot (cache hit, no new
    child); every member journals done; the idle class is asked to shut
    down so the fleet drains."""
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    for n, s in (("t1", 7), ("t2", 8), ("t3", 9)):
        fleet_tool.submit(spool, n, ARGS + ["-s", str(s)], batch=True)
    fleet, spool, stubs = _mk_fleet(tmp_path, clk)
    # drive until the class child is up, then submit the rider
    for _ in range(6):
        fleet.poll_once()
        clk.sleep(0.5)
    leaders = [n for n, j in fleet.jobs.items()
               if n.startswith("serve-") and j.state == "running"]
    assert len(leaders) == 1
    fleet_tool.submit(spool, "t4", ARGS + ["-s", "10"], batch=True)
    _drive(fleet, clk)
    states = {n: j.state for n, j in fleet.jobs.items()}
    assert states[leaders[0]] == "done"
    assert all(states[t] == "done" for t in ("t1", "t2", "t3", "t4"))
    events, recs = _events(spool)
    coal = [r for r in recs if r["event"] == "coalesced"]
    assert len(coal) == 4
    assert [r["cache"] for r in coal].count("hit") == 1
    assert next(r for r in coal if r["job"] == "t4")["cache"] == "hit"
    # one class child total: the rider spawned NO new process
    assert sum(1 for n, _, _ in stubs.spawned
               if n.startswith("serve-")) == 1
    m = read_metrics(os.path.join(spool, "fleet.prom"))
    assert m["avida_fleet_serve_cache_hits_total"] == 1
    assert m["avida_fleet_serve_cache_misses_total"] == 1
    assert m["avida_fleet_serve_promotions_total"] == 4


def test_serve_cancel_demotes_member_alone(tmp_path):
    """Cancelling a serve member demotes only IT: the control loses the
    member, the child retires it, the journal lands `cancelled`, and
    the classmates run on to completion undisturbed."""
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    for n, s in (("c1", 7), ("c2", 8)):
        fleet_tool.submit(spool, n, ARGS + ["-s", str(s)], batch=True)
    fleet, spool, stubs = _mk_fleet(tmp_path, clk,
                                    serve_kw=[{"rate": 2.0}])
    for _ in range(6):
        fleet.poll_once()
        clk.sleep(0.5)
    assert fleet.jobs["c1"].state == "batched"
    fleet_tool.main(["cancel", spool, "c1"])
    _drive(fleet, clk)
    states = {n: j.state for n, j in fleet.jobs.items()}
    assert states["c1"] == "cancelled" and states["c2"] == "done"
    events, _ = _events(spool)
    assert ("cancel_requested", "c1") in events
    assert ("cancelled", "c1") in events
    assert ("done", "c2") in events
    m = read_metrics(os.path.join(spool, "fleet.prom"))
    assert m["avida_fleet_serve_demotions_total"] == 1


def test_serve_replay_reattaches_class_after_orchestrator_kill(tmp_path):
    """The crash-safety acceptance: an orchestrator SIGKILLed mid-churn
    replays its journal, reattaches the serve class from the on-disk
    control file, re-marks its members batched (no solo double-spawn),
    and the tenants complete."""
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    for n, s in (("r1", 7), ("r2", 8)):
        fleet_tool.submit(spool, n, ARGS + ["-s", str(s)], batch=True)
    f1, spool, stubs1 = _mk_fleet(tmp_path, clk,
                                  serve_kw=[{"rate": 0.5}])
    for _ in range(6):
        f1.poll_once()
        clk.sleep(0.5)
    assert {f1.jobs["r1"].state, f1.jobs["r2"].state} == {"batched"}
    # abandon f1 (in-process SIGKILL); the stub child dies with it
    # (same-process emulation), so f2's supervisor restarts the class
    for _, proc, _ in stubs1.spawned:
        proc.kill()
    stubs2 = ServeStubs(clk)
    f2 = FleetOrchestrator(spool, cfg=_cfg(), env=dict(SUP_ENV),
                           clock=clk, sleep=clk.sleep,
                           spawn_factory=stubs2.factory)
    # the reattached class must carry the ORIGINAL member signature
    # (the stored serve_sig): re-hashing the leader's own argv -- which
    # carries --serve-worlds and strips member routing -- would never
    # match an arrival, so every post-restart same-class spec would
    # cold-spawn a duplicate child past the warm one (regression:
    # caught in review, the sig fell back to the leader-argv hash)
    f2.poll_once()
    from avida_tpu.service.serve import static_signature
    member_sig = static_signature(
        {"argv": ARGS + ["-s", "9"], "batch": True},
        with_updates=False)
    assert [c.sig for c in f2.serve_pool.classes.values()] \
        == [member_sig]
    _drive(f2, clk)
    events, _ = _events(spool)
    assert any(e == "serve_reattach" for e, _ in events)
    states = {n: j.state for n, j in f2.jobs.items()}
    assert states["r1"] == "done" and states["r2"] == "done"
    # the members never spawned their own solo children in EITHER life
    solo_spawns = [n for n, _, _ in stubs1.spawned + stubs2.spawned
                   if not n.startswith("serve-")]
    assert solo_spawns == []


def test_serve_leader_failure_requeues_members(tmp_path):
    """A class child that dies terminally (supervisor budget exhausted)
    requeues its members -- their solo-format checkpoints make that
    safe -- and a fresh class picks them up."""
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    for n, s in (("f1", 7), ("f2", 8)):
        fleet_tool.submit(spool, n, ARGS + ["-s", str(s)], batch=True)
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        serve_kw=[{"crash_after": 2.0}, {"crash_after": 2.0},
                  {"crash_after": 2.0}, {}])
    _drive(fleet, clk)
    events, _ = _events(spool)
    assert any(e == "requeued" and j in ("f1", "f2")
               for e, j in events)
    states = {n: j.state for n, j in fleet.jobs.items()}
    assert states["f1"] == "done" and states["f2"] == "done"
    # two classes existed: the crashed one and its replacement
    assert sum(1 for e, _ in events if e == "serve_class") == 2


def test_tenant_quota_holds_overflow_in_queue(tmp_path):
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    for n in ("q1", "q2"):
        fleet_tool.submit(spool, n, ["-u", "10"], tenant="acme")
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        scripts={n: [lambda: ts.FakeProc(clk, code=0, runtime=3.0)]
                 for n in ("q1", "q2")},
        dynamic=False, tenant_max=1, max_jobs=4)
    seen_both_running = []

    real_poll = fleet.poll_once

    def poll():
        active = real_poll()
        running = [n for n, j in fleet.jobs.items()
                   if j.state == "running"]
        seen_both_running.append(len(running))
        return active

    fleet.poll_once = poll
    _drive(fleet, clk)
    assert max(seen_both_running) == 1     # never two acme jobs at once
    assert all(j.state == "done" for j in fleet.jobs.values())


def test_queue_backpressure_bounds_ingestion(tmp_path):
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    for i in range(5):
        fleet_tool.submit(spool, f"b{i}", ["-u", "10"])
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        scripts={f"b{i}": [lambda: ts.FakeProc(clk, code=0,
                                               runtime=1.0)]
                 for i in range(5)},
        dynamic=False, queue_max=2, max_jobs=1)
    fleet.poll_once()
    ingested = sum(1 for j in fleet.jobs.values()
                   if j.state in ("queued", "running"))
    assert ingested <= 3                   # 2 queued + 1 admitted
    _drive(fleet, clk)
    assert all(j.state == "done" for j in fleet.jobs.values())


def test_shard_dirs_scanned_round_robin(tmp_path):
    clk = ts.FakeClock()
    spool = str(tmp_path / "spool")
    paths = [fleet_tool.submit(spool, f"s{i}", ["-u", "10"], shard=3)
             for i in range(4)]
    assert all("/shard-" in p for p in paths)
    fleet, spool, stubs = _mk_fleet(
        tmp_path, clk,
        scripts={f"s{i}": [lambda: ts.FakeProc(clk, code=0,
                                               runtime=1.0)]
                 for i in range(4)},
        dynamic=False, max_jobs=2)
    _drive(fleet, clk)
    assert all(j.state == "done" for j in fleet.jobs.values())
    # fault domains still live at the spool ROOT (shards hold only
    # queued specs)
    for i in range(4):
        assert os.path.isdir(os.path.join(spool, f"s{i}"))
