"""ServeBatch: ghost-padded dynamic membership (parallel/multiworld.py).

The jax side of the streaming serve layer's contract, on the fast XLA
tier: a W=4 class padded from 3 live worlds runs bit-exact vs the 3
solo runs; a rider promoted MID-RUN at a checkpoint boundary reaches
its first executed update with ZERO fresh compiles (the all-ghost
warmup traced every chunk variant; scan_trace_count is the probe) and
finishes bit-exact vs its own solo run; a member demoted at a boundary
leaves a checkpoint byte-identical to the solo generation and resumes
solo bit-exactly.  The packed/Pallas stacked leg and the
SIGKILL-mid-churn orchestrator drill are slow-marked.

Host-only protocol tests live in tests/test_serve.py."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.parallel import multiworld as mwmod
from avida_tpu.parallel.multiworld import ServeBatch
from avida_tpu.utils import checkpoint as ckpt_mod
from avida_tpu.world import World

U = 17
SEEDS = {"m0": 3, "m1": 11, "m2": 29, "m3": 41}
_NB_SCRATCH = ("nb_genome", "nb_len", "nb_cell", "nb_parent",
               "nb_update")


def _cfg(seed, ck=None, **extra):
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.TPU_MAX_MEMORY = 256
    cfg.RANDOM_SEED = seed
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    cfg.set("TPU_CKPT_AUDIT", 0)
    cfg.set("TPU_CKPT_EVERY", 8)
    cfg.set("TPU_CKPT_FINAL", 1)
    cfg.set("TPU_METRICS", 1)
    if ck:
        cfg.set("TPU_CKPT_DIR", str(ck))
    for k, v in extra.items():
        cfg.set(k, v)
    return cfg


def _world(seed, data, ck=None, **extra):
    w = World(cfg=_cfg(seed, ck, **extra), data_dir=str(data))
    w.events = []
    return w


@pytest.fixture(scope="module")
def solo_refs(tmp_path_factory):
    """Uninterrupted solo reference runs (checkpoints on) for every
    tenant the serve legs admit."""
    td = tmp_path_factory.mktemp("solo")
    refs = {}
    for name, s in SEEDS.items():
        w = _world(s, td / name / "d", td / name / "ck")
        w.run(max_updates=U)
        refs[name] = (w, str(td / name / "ck"))
    return refs


def _assert_world_equal(a, b, name, exact_time=True,
                        scratch_exact=True):
    for fname in a.state.__dataclass_fields__:
        va = getattr(a.state, fname)
        if va is None:
            continue
        va = np.asarray(va)
        vb = np.asarray(getattr(b.state, fname))
        if fname in _NB_SCRATCH and not scratch_exact:
            cnt = int(np.asarray(a.state.nb_count))
            va, vb = va[:cnt], vb[:cnt]
        np.testing.assert_array_equal(va, vb,
                                      err_msg=f"{name} field {fname}")
    assert int(np.asarray(a._total_births)) \
        == int(np.asarray(b._total_births)), name
    ta, tb = (float(np.asarray(a._avida_time)),
              float(np.asarray(b._avida_time)))
    if exact_time:
        assert ta == tb, name
    else:
        # a rider's chunk grid differs from solo -> f32 association
        # wiggle in the HOST time accumulator only (device state above
        # is exact)
        assert np.isclose(ta, tb), name
    assert a._flush_exec() == b._flush_exec(), name
    assert a.systematics.num_genotypes == b.systematics.num_genotypes
    assert sorted(g.sequence.tobytes()
                  for g in a.systematics.live_genotypes()) \
        == sorted(g.sequence.tobytes()
                  for g in b.systematics.live_genotypes())


def _member_entry(td, name, **extra):
    e = {"name": name, "seed": SEEDS[name],
         "data_dir": str(td / "serve" / name / "d"),
         "ckpt_dir": str(td / "serve" / name / "ck"),
         "max_updates": U}
    e.update(extra)
    return e


def _write_control(path, members, width=4, shutdown=False):
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"width": width, "shutdown": shutdown,
                   "members": members}, f)
    os.replace(tmp, str(path))


def test_serve_batch_ghost_rider_demotion(solo_refs, tmp_path):
    """The acceptance core on the XLA path, one serve lifetime:

      * W=4 padded from 3 live (slot 3 stays ghost until the rider);
      * boundary u=8: m1 demoted + rider m3 promoted (admitted at the
        u=16 boundary reconcile, starting from ITS update 0 while its
        classmates continue from 16 -- the per-world u0 vector);
      * zero multiworld_scan traces beyond the all-ghost warmup: the
        rider reached its first executed update on warm programs;
      * completed members bit-exact vs their solo runs; the demoted
        member's checkpoint byte-identical to the solo generation and
        solo-resumable bit-exactly."""
    td = tmp_path
    prebuilt = {}

    def factory(entry):
        name = entry["name"]
        if name == "__ghost__":
            return _world(0, entry["data_dir"])
        w = _world(SEEDS[name], entry["data_dir"], entry["ckpt_dir"])
        prebuilt[name] = w
        return w

    ctl = td / "control.json"
    _write_control(ctl, [_member_entry(td, n)
                         for n in ("m0", "m1", "m2")])
    sb = ServeBatch(4, str(ctl), str(td / "serve" / "root"),
                    world_factory=factory)
    traces0 = mwmod.scan_trace_count()

    def hook(s):
        if s.boundaries == 1:
            # at the u=8 boundary: demote m1, queue rider m3 (the next
            # boundary's reconcile admits it)
            _write_control(ctl, [_member_entry(td, n)
                                 for n in ("m0", "m2", "m3")])

    sb._boundary_hook = hook
    real_sleep = time.sleep

    def idle_sleep(sec):
        if not sb._live() and all(
                sb.finished.get(n, {}).get("state") == "done"
                for n in ("m0", "m2", "m3")):
            _write_control(ctl, [_member_entry(td, n)
                                 for n in ("m0", "m2", "m3")],
                           shutdown=True)
        real_sleep(0.01)

    sb._sleep = idle_sleep
    sb.serve()

    # the compile-cache claim: the warmup traced every pow2 chunk
    # variant (1,2,4,8) and NOTHING about the churn -- admission,
    # demotion, ragged per-world updates -- traced a new program
    assert mwmod.scan_trace_count() - traces0 == 4
    assert sb.admissions == 4 and sb.retirements == 4
    # slot bookkeeping: the batch ended all-ghost
    assert sb.num_ghosts == 4 and sb.num_live == 0
    # ghost slots did zero device work: slot 3 was ghost until the
    # rider arrived, and the rider reused m1's freed slot 1 -- so slot
    # 3's lifetime trip count is exactly 0
    assert float(np.asarray(sb._trips)[3]) == 0.0

    # completed members bit-exact vs solo (m0/m2 share the solo chunk
    # grid -> exact host time too; the rider's grid differs)
    _assert_world_equal(solo_refs["m0"][0], prebuilt["m0"], "m0")
    _assert_world_equal(solo_refs["m2"][0], prebuilt["m2"], "m2")
    _assert_world_equal(solo_refs["m3"][0], prebuilt["m3"], "m3",
                        exact_time=False)

    # the demoted member's handoff artifact: its u=16 generation is
    # byte-identical to the solo run's (same grid up to the demotion)
    ua = {ckpt_mod.generation_update(p): p
          for p in ckpt_mod.list_generations(solo_refs["m1"][1])}
    ub = {ckpt_mod.generation_update(p): p
          for p in ckpt_mod.list_generations(
              str(td / "serve" / "m1" / "ck"))}
    assert 16 in ua and 16 in ub
    for fn in sorted(os.listdir(ua[16])):
        with open(os.path.join(ua[16], fn), "rb") as f:
            ba = f.read()
        with open(os.path.join(ub[16], fn), "rb") as f:
            bb = f.read()
        if fn == ckpt_mod.MANIFEST:
            ja, jb = json.loads(ba), json.loads(bb)
            ja.pop("saved_at"), jb.pop("saved_at")
            assert ja == jb, fn
        else:
            assert ba == bb, fn

    # demotion -> solo is a free transition: resume from the serve
    # checkpoint and finish bit-exact vs the uninterrupted solo run
    w1 = _world(SEEDS["m1"], td / "resume" / "d",
                td / "serve" / "m1" / "ck")
    assert w1.resume() == 16
    w1.run(max_updates=U)
    _assert_world_equal(solo_refs["m1"][0], w1, "m1-resumed",
                        scratch_exact=False)

    # observability: serve.json + the two .prom files
    st = json.load(open(td / "serve" / "root" / "serve.json"))
    assert st["width"] == 4 and st["ghosts"] == 4
    assert st["compiles"] >= 4
    from avida_tpu.observability.exporter import read_metrics
    m = read_metrics(str(td / "serve" / "root" / "metrics.prom"))
    assert m["avida_serve_width"] == 4
    assert m["avida_serve_admissions_total"] == 4
    assert m["avida_serve_retirements_total"] == 4


def test_serve_cli_rejects_bad_control(tmp_path):
    from avida_tpu.__main__ import main
    assert main(["--serve-worlds", str(tmp_path / "nope.json")]) == 2
    bad = tmp_path / "ctl.json"
    bad.write_text('{"width": 0, "members": []}')
    assert main(["--serve-worlds", str(bad)]) == 2


@pytest.mark.slow
def test_serve_batch_packed_pallas_rider(tmp_path):
    """The kernel leg: a packed-resident stacked ServeBatch (interpret
    Pallas) serves 2 tenants + ghosts at W=4, admits a rider mid-run,
    and every tenant matches its solo run bit-exactly -- the per-world
    u0 vector composes with the stacked kernel launch and the packed
    whole-chunk residency."""
    from avida_tpu.ops import packed_chunk

    over = dict(TPU_USE_PALLAS=1, TPU_SYSTEMATICS=0, TPU_LANE_PERM=0,
                TPU_KERNEL_SHARDS=1, TPU_PACKED_CHUNK=1,
                TPU_CKPT_EVERY=4)
    UU = 12
    seeds = {"p0": 5, "p1": 9, "p2": 23}
    solos = {}
    for n, s in seeds.items():
        w = _world(s, tmp_path / "solo" / n / "d",
                   tmp_path / "solo" / n / "ck", **over)
        w.run(max_updates=UU)
        solos[n] = w

    prebuilt = {}

    def factory(entry):
        name = entry["name"]
        if name == "__ghost__":
            return _world(0, entry["data_dir"], **over)
        w = _world(seeds[name], entry["data_dir"], entry["ckpt_dir"],
                   **over)
        prebuilt[name] = w
        return w

    def entry(n):
        return {"name": n, "seed": seeds[n],
                "data_dir": str(tmp_path / "serve" / n / "d"),
                "ckpt_dir": str(tmp_path / "serve" / n / "ck"),
                "max_updates": UU}

    ctl = tmp_path / "control.json"
    _write_control(ctl, [entry("p0"), entry("p1")])
    sb = ServeBatch(4, str(ctl), str(tmp_path / "serve" / "root"),
                    world_factory=factory)
    assert packed_chunk.active(sb.params, sb._ghost_state)
    traces0 = mwmod.scan_trace_count()

    def hook(s):
        if s.boundaries == 1:
            _write_control(ctl, [entry(n) for n in seeds])

    sb._boundary_hook = hook
    real_sleep = time.sleep

    def idle_sleep(sec):
        if not sb._live() and all(
                sb.finished.get(n, {}).get("state") == "done"
                for n in seeds):
            _write_control(ctl, [entry(n) for n in seeds],
                           shutdown=True)
        real_sleep(0.01)

    sb._sleep = idle_sleep
    sb.serve()
    assert mwmod.scan_trace_count() - traces0 == 3   # warmup 1,2,4 only
    for n in seeds:
        # the serve boundary grid (every 4) differs from the solo
        # planner's [8,4] grid, so host f32 time association differs;
        # all device state is exact
        _assert_world_equal_nosys(solos[n], prebuilt[n], n,
                                  exact_time=False)


def _assert_world_equal_nosys(a, b, name, exact_time=True):
    for fname in a.state.__dataclass_fields__:
        va = getattr(a.state, fname)
        if va is None:
            continue
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(getattr(b.state, fname)),
            err_msg=f"{name} field {fname}")
    assert int(np.asarray(a._total_births)) \
        == int(np.asarray(b._total_births)), name
    if exact_time:
        assert float(np.asarray(a._avida_time)) \
            == float(np.asarray(b._avida_time)), name
    assert a._flush_exec() == b._flush_exec(), name


# ---------------------------------------------------------------------------
# the SIGKILL-mid-churn drill: real orchestrator, real serve children
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD_SETS = [("WORLD_X", "8"), ("WORLD_Y", "8"),
              ("TPU_MAX_MEMORY", "256"), ("AVE_TIME_SLICE", "100"),
              ("TPU_MAX_STEPS_PER_UPDATE", "100"),
              ("TPU_CKPT_EVERY", "4"), ("TPU_CKPT_AUDIT", "0"),
              ("TPU_SERVE_POLL_SEC", "0.3")]


def _child_args(seed, u):
    args = ["-u", str(u)]
    for n, v in CHILD_SETS:
        args += ["-set", n, v]
    return args + ["-s", str(seed)]


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)  # PR-6 landmine
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    return env


def _spawn_fleet(spool):
    return subprocess.Popen(
        [sys.executable, "-m", "avida_tpu", "--fleet", spool,
         "--dynamic", "--max-jobs", "2"],
        cwd=REPO, env=_env(), stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT)


@pytest.mark.slow
def test_serve_sigkill_mid_churn_resumable(tmp_path):
    """The acceptance drill: tenants stream into a dynamic fleet, the
    ORCHESTRATOR is SIGKILLed mid-churn (no drain, serve child left as
    an orphan), and a fresh orchestrator replays the journal, reaps the
    orphan, reattaches the class and finishes every tenant -- each
    resumable from its own per-world checkpoints, final state bit-exact
    vs an uninterrupted solo run."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import fleet_tool
    from avida_tpu.service.fleet import journal_states

    spool = str(tmp_path / "spool")
    UU = 24
    seeds = {"t1": 7, "t2": 8, "t3": 9}
    for n, s in seeds.items():
        fleet_tool.submit(spool, n, _child_args(s, UU), batch=True)
    proc = _spawn_fleet(spool)
    try:
        # wait for mid-churn evidence: some tenant has a published
        # checkpoint generation (so the kill lands after real progress)
        deadline = time.time() + 420
        while time.time() < deadline:
            if any(ckpt_mod.list_generations(os.path.join(spool, n,
                                                          "ck"))
                   for n in seeds):
                break
            time.sleep(2)
        else:
            raise AssertionError("no tenant checkpointed in time")
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()
    finally:
        if proc.poll() is None:
            proc.kill()

    # fresh orchestrator: replay + orphan reap + reattach + finish
    proc2 = _spawn_fleet(spool)
    try:
        assert proc2.wait(timeout=600) == 0
    finally:
        if proc2.poll() is None:
            proc2.kill()
            proc2.wait()
    st, _, _ = journal_states(os.path.join(spool, "fleet.jsonl"))
    assert all(st[n] == "done" for n in seeds), st

    # bit-exactness: every tenant's final checkpoint equals an
    # uninterrupted in-process solo run with the same resolved config
    for n, s in seeds.items():
        cfg = AvidaConfig()
        for k, v in CHILD_SETS:
            cfg.set(k, v)
        cfg.set("RANDOM_SEED", s)
        cfg.set("TPU_METRICS", 1)
        cfg.set("TPU_CKPT_FINAL", 1)
        solo = World(cfg=cfg, data_dir=str(tmp_path / "ref" / n))
        solo.run(max_updates=UU)
        cfg2 = AvidaConfig()
        for k, v in CHILD_SETS:
            cfg2.set(k, v)
        cfg2.set("RANDOM_SEED", s)
        restored = World(cfg=cfg2,
                         data_dir=str(tmp_path / "res" / n))
        assert restored.resume(os.path.join(spool, n, "ck")) == UU
        for fname in solo.state.__dataclass_fields__:
            va = getattr(solo.state, fname)
            if va is None:
                continue
            va = np.asarray(va)
            vb = np.asarray(getattr(restored.state, fname))
            if fname in _NB_SCRATCH:
                cnt = int(np.asarray(solo.state.nb_count))
                va, vb = va[:cnt], vb[:cnt]
            np.testing.assert_array_equal(
                va, vb, err_msg=f"{n} field {fname}")
