"""Sexual reproduction: divide-sex, birth-chamber pairing, crossover.

Covers BASELINE.json config 3 (heads-sex + recombination).  Reference
semantics: cBirthChamber::SubmitOffspring (cBirthChamber.cc:443) stores a
sexual offspring until a mate arrives, DoBasicRecombination (cc:290) swaps
one random region between the two genomes (RegionSwap cc:178) and mixes
merits by the cut fraction; modeled on the reference `sex` test scenario.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from avida_tpu.config import AvidaConfig, heads_sex_instset
from avida_tpu.core.state import make_world_params, zeros_population
from avida_tpu.ops import birth as birth_ops
from avida_tpu.world import World

pytestmark = pytest.mark.slow


def _sex_params(n_side=4, L=64):
    cfg = AvidaConfig()
    cfg.WORLD_X = n_side
    cfg.WORLD_Y = n_side
    cfg.TPU_MAX_MEMORY = L
    cfg.RANDOM_SEED = 3
    cfg.DIVIDE_INS_PROB = 0.0     # keep offspring content deterministic
    cfg.DIVIDE_DEL_PROB = 0.0
    cfg.COPY_MUT_PROB = 0.0
    from avida_tpu.config.environment import default_logic9_environment
    return make_world_params(cfg, heads_sex_instset(),
                             default_logic9_environment())


def _pending_pair_state(params, len_a=40, len_b=40):
    """Two alive organisms with pending sexual offspring of known content:
    parent 0's offspring is all opcode 1, parent 5's is all opcode 2."""
    n, L, R = params.num_cells, params.max_memory, params.num_reactions
    st = zeros_population(n, L, R)
    tape = np.zeros((n, L), np.uint8)
    # offspring bytes live on the tape after the divide point (off_start)
    tape[0, :len_a] = 1
    tape[5, :len_b] = 2
    return st.replace(
        tape=jnp.asarray(tape),
        genome=jnp.asarray(tape.astype(np.int8)),
        alive=jnp.zeros(n, bool).at[0].set(True).at[5].set(True),
        merit=jnp.zeros(n, jnp.float32).at[0].set(100.0).at[5].set(300.0),
        divide_pending=jnp.zeros(n, bool).at[0].set(True).at[5].set(True),
        off_sex=jnp.zeros(n, bool).at[0].set(True).at[5].set(True),
        off_start=jnp.zeros(n, jnp.int32),
        off_len=jnp.zeros(n, jnp.int32).at[0].set(len_a).at[5].set(len_b),
        mem_len=jnp.zeros(n, jnp.int32).at[0].set(len_a).at[5].set(len_b),
        genome_len=jnp.zeros(n, jnp.int32).at[0].set(len_a).at[5].set(len_b),
    )


def test_paired_offspring_are_two_parent_recombinants():
    params = _sex_params()
    st = _pending_pair_state(params)
    pending = st.divide_pending & st.alive
    off_mem = st.genome
    off_len = st.off_len
    (off_mem, off_len, child_merit, placeable, dual, dual_mem, dual_len,
     dual_merit, store) = birth_ops.recombine_sexual(
        params, st, jax.random.key(7), off_mem, off_len, pending)

    c0 = np.asarray(off_mem[0])[: int(off_len[0])]
    c5 = np.asarray(off_mem[5])[: int(off_len[5])]
    # both children carry material from BOTH parents (opcodes 1 and 2)
    assert set(np.unique(c0)) == {1, 2}, c0
    assert set(np.unique(c5)) == {1, 2}, c5
    # child 0 keeps parent-0 flanks, child 5 keeps parent-5 flanks
    assert c0[0] == 1 and c0[-1] == 1
    assert c5[0] == 2 and c5[-1] == 2
    # the swapped region is complementary: counts of foreign material match
    assert (c0 == 2).sum() == int(off_len[0]) - (c0 == 1).sum()
    # lengths complementary: total material conserved
    assert int(off_len[0]) + int(off_len[5]) == 80
    # merit mixing moves both toward the other parent
    m0, m5 = float(child_merit[0]), float(child_merit[5])
    assert 100.0 <= m0 <= 300.0 and 100.0 <= m5 <= 300.0
    assert abs((m0 + m5) - 400.0) < 1e-3      # merit conserved
    # both were paired, nothing waits
    assert bool(placeable[0]) and bool(placeable[5])
    assert not bool(store[3])                  # store empty


def test_odd_offspring_waits_in_store_and_parent_resumes():
    params = _sex_params()
    st = _pending_pair_state(params)
    # only parent 0 divides this flush
    st = st.replace(divide_pending=st.divide_pending.at[5].set(False),
                    off_sex=st.off_sex.at[5].set(False))
    neighbors = jnp.asarray(birth_ops.neighbor_table(
        params.world_x, params.world_y, params.geometry))
    st2 = birth_ops.flush_births(params, st, jax.random.key(1), neighbors,
                                 jnp.int32(0))
    # offspring moved into the chamber store; parent resumed (not pending)
    assert bool(st2.bc_valid)
    assert int(st2.bc_len) == 40
    assert not bool(st2.divide_pending[0])
    # nothing was born yet
    assert int(st2.alive.sum()) == 2
    # a second sexual offspring now pairs WITH the store: seed parent 5
    st3 = st2.replace(
        divide_pending=st2.divide_pending.at[5].set(True),
        off_sex=st2.off_sex.at[5].set(True))
    st4 = birth_ops.flush_births(params, st3, jax.random.key(2), neighbors,
                                 jnp.int32(1))
    # two children born from the pair (dual placement), store drained
    assert int(st4.alive.sum()) == 4
    assert not bool(st4.bc_valid)
    born = np.asarray(st4.alive & (st4.birth_update == 1))
    cells = np.nonzero(born)[0]
    assert len(cells) == 2
    kids = [np.asarray(st4.genome[c])[: int(st4.genome_len[c])]
            for c in cells]
    # with RECOMBINATION_PROB=1 both children are two-parent recombinants
    assert all(set(np.unique(k)) == {1, 2} for k in kids), kids


def test_sexual_world_sustains_population():
    cfg = AvidaConfig()
    cfg.WORLD_X = 10
    cfg.WORLD_Y = 10
    cfg.TPU_MAX_MEMORY = 320
    cfg.RANDOM_SEED = 17
    cfg.INST_SET = "heads_sex"
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    cfg.set("TPU_SYSTEMATICS", 0)
    w = World(cfg=cfg)
    assert "divide-sex" in w.instset.inst_names
    w.inject()
    w.run(max_updates=30)
    # a lone sexual ancestor must not deadlock: its first offspring waits
    # in the chamber, the parent resumes, the second offspring mates with
    # the first, and the population grows
    assert w.num_organisms > 2, w.num_organisms
