"""Run supervisor + fault-injection unit tier (service/, utils/faultinject).

Everything here is host-only and drives the supervisor with a FAKE
clock, FAKE sleeps and SCRIPTED fake child processes -- no jax, no real
subprocesses, no real time.  The end-to-end chaos proofs with real
children live in tests/test_chaos.py.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

from avida_tpu.service import EXIT_AUDIT, EXIT_CKPT
from avida_tpu.service.backoff import RetryPolicy
from avida_tpu.service.supervisor import (Supervisor, SupervisorConfig,
                                          classify, pallas_suspect)
from avida_tpu.utils import checkpoint as ckpt_mod
from avida_tpu.utils import faultinject as fi

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
import ckpt_tool  # noqa: E402


# ---------------------------------------------------------------------------
# backoff / retry budget (fake clock: zero real sleeps)
# ---------------------------------------------------------------------------

def test_backoff_cap_and_jitter_bounds():
    p = RetryPolicy(max_retries=50, base=0.5, cap=8.0, seed=3)
    prev = 0.5
    for _ in range(50):
        d = p.next_delay()
        assert 0.5 <= d <= 8.0                    # cap honored, base floor
        assert d <= max(prev * 3, 0.5) + 1e-9     # decorrelated jitter bound
        prev = d
    assert not p.can_retry()


def test_backoff_delays_are_seeded_and_decorrelated():
    a = [RetryPolicy(seed=7).next_delay() for _ in range(1)]
    b = [RetryPolicy(seed=7).next_delay() for _ in range(1)]
    assert a == b                                  # reproducible
    c = RetryPolicy(seed=8).next_delay()
    assert c != a[0]                               # seed actually used
    p = RetryPolicy(seed=7)
    ds = [p.next_delay() for _ in range(6)]
    assert len(set(round(d, 6) for d in ds)) > 1   # jittered, not a ladder


def test_backoff_budget_resets_after_sustained_health():
    p = RetryPolicy(max_retries=2, base=1.0, cap=30.0, healthy_sec=60.0)
    p.next_delay()
    p.next_delay()
    assert not p.can_retry()
    assert not p.note_healthy(59.9)                # not sustained yet
    assert not p.can_retry()
    assert p.note_healthy(60.0)                    # refill
    assert p.can_retry() and p.budget_left() == 2
    # and the backoff ladder restarts from base
    assert p.next_delay() <= 3.0


def test_backoff_rejects_bad_window():
    with pytest.raises(ValueError):
        RetryPolicy(base=0.0)
    with pytest.raises(ValueError):
        RetryPolicy(base=2.0, cap=1.0)


# ---------------------------------------------------------------------------
# TPU_FAULT spec grammar
# ---------------------------------------------------------------------------

def test_fault_spec_grammar():
    (f,) = fi.parse_spec("crash@update=120")
    assert f.kind == "crash" and f.trigger == ("update", 120)
    (f,) = fi.parse_spec("sigkill@chunk=3")
    assert f.trigger == ("chunk", 3)
    (f,) = fi.parse_spec("corrupt-ckpt:leaf=merit")
    assert f.args == {"leaf": "merit"} and f.trigger is None
    (f,) = fi.parse_spec("nan:merit@update=200")   # bare value -> leaf
    assert f.kind == "nan" and f.args == {"leaf": "merit"}
    (f,) = fi.parse_spec("hang:sec=5@chunk=2")
    assert float(f.args["sec"]) == 5.0
    two = fi.parse_spec(" corrupt-ckpt:leaf=merit ; sigkill@update=8 ")
    assert [x.kind for x in two] == ["corrupt-ckpt", "sigkill"]


def test_save_kinds_reject_chunk_triggers():
    # save-time faults fire on checkpoint publishes; a @chunk trigger
    # would be silently meaningless there, so the parser refuses it
    with pytest.raises(ValueError, match="save-time kinds"):
        fi.parse_spec("corrupt-ckpt@chunk=3")
    with pytest.raises(ValueError, match="save-time kinds"):
        fi.parse_spec("torn-manifest@chunk=1")
    fi.parse_spec("corrupt-ckpt@update=8")         # @update stays legal


def test_fault_spec_rejects_garbage():
    with pytest.raises(ValueError, match="unknown fault kind"):
        fi.parse_spec("meteor@update=1")
    with pytest.raises(ValueError, match="nan requires @update"):
        fi.parse_spec("nan:merit")
    with pytest.raises(ValueError, match="nan requires @update"):
        fi.parse_spec("nan:merit@chunk=2")
    with pytest.raises(ValueError, match="leaf must be one of"):
        fi.parse_spec("nan:alive@update=3")
    with pytest.raises(ValueError, match="trigger"):
        fi.parse_spec("crash@whenever=1")
    with pytest.raises(ValueError, match="no bare argument"):
        fi.parse_spec("crash:hard")
    with pytest.raises(ValueError, match="empty"):
        fi.parse_spec(" ; ")


def test_fault_due_semantics():
    (f,) = fi.parse_spec("crash@update=10")
    assert not f.due(update=9, chunk=99) and f.due(update=10, chunk=1)
    (f,) = fi.parse_spec("crash@chunk=2")
    assert not f.due(update=99, chunk=1) and f.due(update=0, chunk=2)
    (f,) = fi.parse_spec("crash")
    assert f.due(update=0, chunk=1)                # first boundary


def test_fault_seeding_is_deterministic():
    a = fi.parse_spec("torn-manifest", seed=5)[0].rng.random()
    b = fi.parse_spec("torn-manifest", seed=5)[0].rng.random()
    c = fi.parse_spec("torn-manifest", seed=6)[0].rng.random()
    assert a == b and a != c


# ---------------------------------------------------------------------------
# host-side corruption helpers against real generation dirs
# ---------------------------------------------------------------------------

def _gen(base, update=1, keep=4):
    arrays = {"state.merit": np.linspace(0, 1, 64).astype(np.float32),
              "state.alive": np.ones(64, bool)}
    return ckpt_mod.write_generation(str(base), update, arrays,
                                     {"update": update}, keep=keep)


def test_corrupt_leaf_is_crc_detectable(tmp_path):
    path = _gen(tmp_path / "ck")
    fi.corrupt_leaf(path, "merit", fi.parse_spec("corrupt-ckpt", seed=1)[0].rng)
    with pytest.raises(ckpt_mod.CheckpointError, match="CRC mismatch"):
        ckpt_mod.verify_generation(path)
    with pytest.raises(ValueError, match="no state.fitness"):
        fi.corrupt_leaf(path, "fitness")


def test_tear_manifest_is_distinct_error_class(tmp_path):
    path = _gen(tmp_path / "ck")
    kept = fi.tear_manifest(path)
    assert 0 <= kept < os.path.getsize(os.path.join(path, "manifest.json")) \
        + 1
    with pytest.raises(ckpt_mod.CheckpointManifestError, match="manifest"):
        ckpt_mod.verify_generation(path)
    # the torn-manifest class is still a CheckpointError (restore
    # fallback catches one type), but NOT a CRC mismatch
    assert issubclass(ckpt_mod.CheckpointManifestError,
                      ckpt_mod.CheckpointError)


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def test_failure_classification_table():
    assert classify(0) == "success"
    assert classify(0, preempted=True) == "preempt"
    assert classify(1) == "crash"
    assert classify(-9) == "crash"                 # SIGKILL'd from outside
    assert classify(EXIT_AUDIT) == "audit_violation"
    assert classify(EXIT_CKPT) == "corrupt_ckpt"
    assert classify(-9, watchdog_killed=True) == "hang"
    assert classify(0, anomaly_killed=True) == "audit_violation"
    # supervisor-initiated kills outrank the exit code they caused
    assert classify(EXIT_AUDIT, watchdog_killed=True) == "hang"


def test_pallas_suspect_matcher():
    assert pallas_suspect("jax._src.pallas.mosaic.lowering: boom")
    assert pallas_suspect("Mosaic failed to compile")
    assert not pallas_suspect("ValueError: seed genome length")


# ---------------------------------------------------------------------------
# the supervision loop, driven by fakes
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.t += s


class FakeProc:
    """Scripted child: exits with `code` after `runtime` fake seconds
    (None = runs until killed).  `poll_hook(proc, elapsed)` runs at
    every supervisor poll so scenarios can refresh heartbeats or plant
    anomalies mid-flight."""

    def __init__(self, clock, code=0, runtime=0.0, on_spawn=None,
                 poll_hook=None):
        self.clock = clock
        self.code = code
        self.runtime = runtime
        self.on_spawn = on_spawn
        self.poll_hook = poll_hook
        self.returncode = None
        self.pid = 4242
        self.t0 = None

    def _spawned(self, argv, env, logf):
        self.t0 = self.clock()
        self.argv, self.env = argv, env
        if self.on_spawn:
            self.on_spawn(self, argv, env, logf)

    def poll(self):
        if self.returncode is None and self.t0 is not None:
            elapsed = self.clock() - self.t0
            if self.poll_hook:
                self.poll_hook(self, elapsed)
            if self.returncode is None and self.runtime is not None \
                    and elapsed >= self.runtime:
                self.returncode = self.code
        return self.returncode

    def wait(self, timeout=None):
        if self.poll() is None:
            if self.runtime is None:
                raise AssertionError("wait() on a hung FakeProc")
            self.clock.t = self.t0 + self.runtime
            self.returncode = self.code
        return self.returncode

    def kill(self):
        if self.returncode is None:
            self.returncode = -9

    def terminate(self):
        if self.returncode is None:
            self.returncode = 0            # graceful preempt path

    def send_signal(self, sig):
        self.terminate()


def _write_metrics(data_dir, hb, preempted=0, anomalies=None, update=42):
    os.makedirs(data_dir, exist_ok=True)
    lines = [f"avida_heartbeat_timestamp_seconds {hb}",
             f"avida_preempted {preempted}",
             f"avida_update {update}"]
    if anomalies is not None:
        lines.append(
            f'avida_trace_code_total{{code="anom_merit"}} {anomalies}')
    with open(os.path.join(data_dir, "metrics.prom"), "w") as f:
        f.write("\n".join(lines) + "\n")


def _mk_sup(tmp_path, procs, clock, **cfg_kw):
    data = tmp_path / "data"
    ck = tmp_path / "ck"
    os.makedirs(ck, exist_ok=True)
    seq = list(procs)
    spawned = []

    def spawn(argv, env, logf):
        proc = seq.pop(0)
        proc._spawned(argv, env, logf)
        spawned.append(proc)
        return proc

    kw = dict(watchdog_sec=10.0, poll_sec=0.5, grace_sec=30.0,
              max_retries=4, backoff_base=0.1, backoff_cap=1.0,
              healthy_sec=1e9, seed=2)
    kw.update(cfg_kw)
    sup = Supervisor(
        ["-d", str(data), "-set", "TPU_CKPT_DIR", str(ck), "-u", "100"],
        cfg=SupervisorConfig(**kw), env={}, spawn=spawn,
        clock=clock, sleep=clock.sleep)
    return sup, str(data), str(ck), spawned


def _runlog_events(data_dir):
    path = os.path.join(data_dir, "supervisor.jsonl")
    recs = [json.loads(line) for line in open(path)]
    assert all(r["record"] == "supervisor" for r in recs)
    return [r["event"] for r in recs], recs


def test_supervisor_forces_metrics_and_resume_flags(tmp_path):
    clk = FakeClock()
    sup, _, _, _ = _mk_sup(tmp_path, [], clk)
    assert "--resume" in sup.child_argv
    assert "TPU_METRICS" in sup.child_argv


def test_supervisor_rejects_unsupervisable_child_argv(tmp_path):
    with pytest.raises(ValueError, match="data dir"):
        Supervisor(["-set", "TPU_CKPT_DIR", str(tmp_path)], env={})
    with pytest.raises(ValueError, match="TPU_CKPT_DIR"):
        Supervisor(["-d", str(tmp_path)], env={})
    with pytest.raises(ValueError, match="fault-plan"):
        Supervisor(["-d", str(tmp_path), "-set", "TPU_CKPT_DIR",
                    str(tmp_path), "-set", "TPU_FAULT", "crash"], env={})
    # an explicit heartbeat opt-out would blind the watchdog
    with pytest.raises(ValueError, match="heartbeat"):
        Supervisor(["-d", str(tmp_path), "-set", "TPU_CKPT_DIR",
                    str(tmp_path), "-set", "TPU_METRICS", "0"], env={})


def test_success_first_boot(tmp_path):
    clk = FakeClock()

    def finish(proc, argv, env, logf):
        _write_metrics(os.path.dirname(logf.name), hb=clk())

    sup, data, _, spawned = _mk_sup(
        tmp_path, [FakeProc(clk, code=0, runtime=0.0, on_spawn=finish)], clk)
    assert sup.run() == 0
    assert sup.boots == 1 and sup.restarts == 0
    events, _ = _runlog_events(data)
    assert events[0] == "launch" and "done" in events
    # metrics file published and parseable
    from avida_tpu.observability.exporter import read_metrics
    m = read_metrics(os.path.join(data, "supervisor.prom"))
    assert m["avida_supervisor_boots_total"] == 1
    assert m['avida_supervisor_failures_total{class="crash"}'] == 0


def test_crash_restarts_with_backoff_then_budget_exhausts(tmp_path):
    clk = FakeClock()
    procs = [FakeProc(clk, code=1, runtime=0.0) for _ in range(5)]
    sup, data, _, _ = _mk_sup(tmp_path, procs, clk, max_retries=4)
    t0 = clk()
    assert sup.run() == 1                          # gave up
    assert sup.boots == 5 and sup.failures["crash"] == 5
    assert not sup.policy.can_retry()
    # backoff actually slept: 4 jittered delays in [base, cap]
    assert 4 * 0.1 <= clk() - t0 <= 4 * 1.0 + 5 * 0.5 + 1
    events, recs = _runlog_events(data)
    assert events.count("backoff") == 4 and "giving_up" in events
    delays = [r["delay_sec"] for r in recs if r["event"] == "backoff"]
    assert all(0.1 <= d <= 1.0 for d in delays)


def test_watchdog_kills_stale_heartbeat_and_recovers(tmp_path):
    clk = FakeClock()

    def beat_once(proc, argv, env, logf):
        _write_metrics(os.path.dirname(logf.name), hb=clk())

    def finish(proc, argv, env, logf):
        _write_metrics(os.path.dirname(logf.name), hb=clk())

    hung = FakeProc(clk, runtime=None, on_spawn=beat_once)
    ok = FakeProc(clk, code=0, runtime=0.0, on_spawn=finish)
    sup, data, _, _ = _mk_sup(tmp_path, [hung, ok], clk, watchdog_sec=10.0)
    assert sup.run() == 0
    assert sup.failures["hang"] == 1 and sup.watchdog_kills == 1
    assert hung.returncode == -9                   # SIGKILL, not SIGTERM
    events, _ = _runlog_events(data)
    assert "watchdog_kill" in events


def test_watchdog_grace_covers_slow_first_heartbeat(tmp_path):
    clk = FakeClock()

    def late_beat(proc, elapsed):
        # first heartbeat only after 20s of jit compilation -- well past
        # watchdog_sec but inside grace_sec
        if elapsed >= 20.0:
            _write_metrics(proc._data, hb=clk())
            if elapsed >= 21.0:
                proc.returncode = 0

    proc = FakeProc(clk, runtime=None, poll_hook=late_beat)
    sup, data, _, _ = _mk_sup(tmp_path, [proc], clk,
                              watchdog_sec=5.0, grace_sec=60.0)
    proc._data = str(tmp_path / "data")
    assert sup.run() == 0
    assert sup.watchdog_kills == 0


def test_stale_previous_heartbeat_does_not_insta_kill_restart(tmp_path):
    clk = FakeClock()
    data = str(tmp_path / "data")
    # a PREVIOUS boot's heartbeat, very stale by now
    _write_metrics(data, hb=clk() - 500.0)

    def finish(proc, argv, env, logf):
        pass                                       # exits before beating

    def slow_finish(proc, elapsed):
        if elapsed >= 15.0:                        # past watchdog_sec
            _write_metrics(data, hb=clk())
            proc.returncode = 0

    proc = FakeProc(clk, runtime=None, poll_hook=slow_finish)
    sup, _, _, _ = _mk_sup(tmp_path, [proc], clk,
                           watchdog_sec=5.0, grace_sec=60.0)
    assert sup.run() == 0
    assert sup.watchdog_kills == 0                 # grace clock governed


def test_preempt_relaunches_without_consuming_budget(tmp_path):
    clk = FakeClock()

    def preempted(proc, argv, env, logf):
        _write_metrics(os.path.dirname(logf.name), hb=clk(), preempted=1)

    def finish(proc, argv, env, logf):
        _write_metrics(os.path.dirname(logf.name), hb=clk(), preempted=0)

    procs = [FakeProc(clk, code=0, runtime=0.0, on_spawn=preempted),
             FakeProc(clk, code=0, runtime=0.0, on_spawn=finish)]
    sup, data, _, _ = _mk_sup(tmp_path, procs, clk)
    assert sup.run() == 0
    assert sup.failures["preempt"] == 1
    assert sup.policy.failures == 0                # no budget consumed
    events, _ = _runlog_events(data)
    assert "restart" in events


def test_audit_violation_rolls_back_newest_generation(tmp_path):
    clk = FakeClock()
    ck = tmp_path / "ck"
    old = _gen(ck, update=10)
    new = _gen(ck, update=20)

    def finish(proc, argv, env, logf):
        _write_metrics(os.path.dirname(logf.name), hb=clk())

    procs = [FakeProc(clk, code=EXIT_AUDIT, runtime=0.0),
             FakeProc(clk, code=0, runtime=0.0, on_spawn=finish)]
    sup, data, _, _ = _mk_sup(tmp_path, procs, clk)
    assert sup.run() == 0
    assert sup.failures["audit_violation"] == 1 and sup.rollbacks == 1
    gens = ckpt_mod.list_generations(str(ck))
    assert gens == [old]                           # newest quarantined
    quarantined = [d for d in os.listdir(ck) if d.startswith(".bad-")]
    assert len(quarantined) == 1
    assert os.path.basename(new) in quarantined[0]
    # the quarantine is invisible to resume's candidate scan
    assert ckpt_mod.restore_candidates(str(ck)) == [old]
    events, _ = _runlog_events(data)
    assert "rollback" in events


def test_audit_rollback_keeps_a_sole_generation(tmp_path):
    clk = FakeClock()
    ck = tmp_path / "ck"
    only = _gen(ck, update=10)
    procs = [FakeProc(clk, code=EXIT_AUDIT, runtime=0.0),
             FakeProc(clk, code=0, runtime=0.0)]
    sup, data, _, _ = _mk_sup(tmp_path, procs, clk)
    assert sup.run() == 0
    assert ckpt_mod.list_generations(str(ck)) == [only]
    events, _ = _runlog_events(data)
    assert "rollback_skipped" in events


def test_anomaly_onset_triggers_graceful_stop_and_rollback(tmp_path):
    clk = FakeClock()
    ck = tmp_path / "ck"
    _gen(ck, update=10)
    _gen(ck, update=20)
    data = str(tmp_path / "data")

    def evolving(proc, elapsed):
        # healthy heartbeats, then a flight-recorder anomaly shows up
        _write_metrics(data, hb=clk(),
                       anomalies=0 if elapsed < 3.0 else 1)

    def finish(proc, argv, env, logf):
        _write_metrics(data, hb=clk(), anomalies=1)

    procs = [FakeProc(clk, runtime=None, poll_hook=evolving),
             FakeProc(clk, code=0, runtime=0.0, on_spawn=finish)]
    sup, _, _, _ = _mk_sup(tmp_path, procs, clk)
    assert sup.run() == 0
    assert procs[0].returncode == 0                # SIGTERM, not SIGKILL
    assert sup.failures["audit_violation"] == 1 and sup.rollbacks == 1
    assert len(ckpt_mod.list_generations(str(ck))) == 1
    events, _ = _runlog_events(data)
    assert "anomaly_detected" in events
    # boot 2's anomaly baseline resets: the restored counter (still 1)
    # must not re-trigger -- proven by the clean exit above


def test_pallas_crash_degrades_to_xla_once(tmp_path):
    clk = FakeClock()

    def pallas_boom(proc, argv, env, logf):
        logf.write("jax._src.pallas.mosaic.lowering.LoweringError: bad\n")
        logf.flush()

    def finish(proc, argv, env, logf):
        _write_metrics(os.path.dirname(logf.name), hb=clk())

    procs = [FakeProc(clk, code=1, runtime=0.0, on_spawn=pallas_boom),
             FakeProc(clk, code=0, runtime=0.0, on_spawn=finish)]
    sup, data, _, spawned = _mk_sup(tmp_path, procs, clk)
    assert sup.run() == 0
    assert sup.pallas_fallbacks == 1
    assert sup.policy.failures == 0                # the free retry
    argv2 = spawned[1].argv
    i = argv2.index("TPU_USE_PALLAS")
    assert argv2[i - 1] == "-set" and argv2[i + 1] == "2"
    events, _ = _runlog_events(data)
    assert "pallas_fallback" in events


def test_corrupt_ckpt_fallback_is_recorded_even_on_success(tmp_path):
    clk = FakeClock()

    def fallback_then_finish(proc, argv, env, logf):
        # the fallback marker lands at boot START (resume time); a
        # chatty child then writes far more than the 8 KB tail window --
        # classification must still see the head of the boot's log
        logf.write("[avida-tpu] checkpoint_corrupt: path=gen error=CRC\n")
        logf.write("chatter\n" * 4000)
        logf.flush()
        _write_metrics(os.path.dirname(logf.name), hb=clk())

    sup, data, _, _ = _mk_sup(
        tmp_path,
        [FakeProc(clk, code=0, runtime=0.0, on_spawn=fallback_then_finish)],
        clk)
    assert sup.run() == 0
    assert sup.failures["corrupt_ckpt"] == 1 and sup.ckpt_fallbacks == 1
    from avida_tpu.observability.exporter import read_metrics
    m = read_metrics(os.path.join(data, "supervisor.prom"))
    assert m["avida_supervisor_ckpt_fallbacks_total"] == 1
    assert m['avida_supervisor_failures_total{class="corrupt_ckpt"}'] == 1


def test_corrupt_ckpt_counted_once_per_generation_not_per_boot(tmp_path):
    """The corrupt generation stays on disk after CRC fallback, so
    every later boot's resume re-logs the same path -- ONE corruption
    event must not inflate the counter once per boot."""
    clk = FakeClock()

    def log_fallback(proc, argv, env, logf):
        logf.write("[avida-tpu] checkpoint_corrupt: path=/ck/gen-8 "
                   "error=CRC\n")
        logf.flush()
        _write_metrics(os.path.dirname(logf.name), hb=clk())

    procs = [FakeProc(clk, code=1, runtime=0.0, on_spawn=log_fallback),
             FakeProc(clk, code=0, runtime=0.0, on_spawn=log_fallback)]
    sup, data, _, _ = _mk_sup(tmp_path, procs, clk)
    assert sup.run() == 0
    assert sup.failures["corrupt_ckpt"] == 1       # one generation
    assert sup.ckpt_fallbacks == 1
    assert sup.failures["crash"] == 1              # boot 1 still a crash


def test_fault_plan_is_consumed_one_spec_per_boot(tmp_path):
    clk = FakeClock()
    procs = [FakeProc(clk, code=1, runtime=0.0) for _ in range(3)]
    sup, _, _, spawned = _mk_sup(tmp_path, procs, clk, max_retries=2)
    sup.fault_plan = ["sigkill@update=5", "sigkill@update=9"]
    assert sup.run() == 1
    assert spawned[0].env.get("TPU_FAULT") == "sigkill@update=5"
    assert spawned[1].env.get("TPU_FAULT") == "sigkill@update=9"
    assert "TPU_FAULT" not in spawned[2].env       # plan exhausted


def test_healthy_interval_resets_budget(tmp_path):
    clk = FakeClock()
    data = str(tmp_path / "data")

    def long_healthy(proc, elapsed):
        _write_metrics(data, hb=clk())
        if elapsed >= 50.0:
            proc.returncode = 0

    procs = [FakeProc(clk, code=1, runtime=0.0),
             FakeProc(clk, runtime=None, poll_hook=long_healthy)]
    sup, _, _, _ = _mk_sup(tmp_path, procs, clk, healthy_sec=20.0)
    assert sup.run() == 0
    assert sup.policy.failures == 0                # refilled mid-boot-2
    events, _ = _runlog_events(data)
    assert "budget_reset" in events


# ---------------------------------------------------------------------------
# liveness-gap watchdogs: progress counter + backwards heartbeat
# ---------------------------------------------------------------------------

def test_progress_watchdog_kills_livelocked_child(tmp_path):
    """A livelocked child can keep touching its heartbeat file while
    making no progress -- with TPU_PROGRESS_SEC set, the watchdog also
    requires the avida_update counter to ADVANCE."""
    clk = FakeClock()
    data = str(tmp_path / "data")

    def livelocked(proc, elapsed):
        # fresh heartbeats forever, update counter frozen at 42
        _write_metrics(data, hb=clk(), update=42)

    def finish(proc, argv, env, logf):
        _write_metrics(data, hb=clk(), update=100)

    procs = [FakeProc(clk, runtime=None, poll_hook=livelocked),
             FakeProc(clk, code=0, runtime=0.0, on_spawn=finish)]
    sup, _, _, _ = _mk_sup(tmp_path, procs, clk,
                           watchdog_sec=30.0, progress_sec=5.0)
    t0 = clk()
    assert sup.run() == 0
    assert sup.failures["hang"] == 1 and sup.watchdog_kills == 1
    assert procs[0].returncode == -9
    # killed on the progress clock, well before heartbeat staleness
    # could ever fire (heartbeats stayed fresh throughout)
    assert clk() - t0 < 30.0
    events, recs = _runlog_events(data)
    kills = [r for r in recs if r["event"] == "watchdog_kill"]
    assert kills[0]["reason"] == "no progress"


def test_progress_watchdog_spares_advancing_child(tmp_path):
    clk = FakeClock()
    data = str(tmp_path / "data")

    def advancing(proc, elapsed):
        _write_metrics(data, hb=clk(), update=int(elapsed))
        if elapsed >= 20.0:
            proc.returncode = 0

    procs = [FakeProc(clk, runtime=None, poll_hook=advancing)]
    sup, _, _, _ = _mk_sup(tmp_path, procs, clk,
                           watchdog_sec=30.0, progress_sec=5.0)
    assert sup.run() == 0
    assert sup.watchdog_kills == 0


def test_progress_watchdog_defaults_off():
    cfg = SupervisorConfig.from_env({})
    assert cfg.progress_sec == 0.0
    cfg = SupervisorConfig.from_env({"TPU_PROGRESS_SEC": "7.5"})
    assert cfg.progress_sec == 7.5


def test_backwards_heartbeat_is_stale_not_fresh(tmp_path):
    """A heartbeat timestamp that moves BACKWARDS (stepped host clock)
    must never count as fresh: without the hb_max guard, `now - hb`
    stays small and a wedged child with a back-stepped clock would look
    alive forever."""
    clk = FakeClock()
    data = str(tmp_path / "data")

    def stepped_clock(proc, elapsed):
        if elapsed < 3.0:
            _write_metrics(data, hb=clk())
        else:
            # the child's clock stepped back 15s (> watchdog_sec): every
            # later beat regresses below the max already seen, so none
            # may count as an advance -- the kill must fire on the
            # last-true-advance clock, BEFORE the stepped timestamps
            # crawl back past the old maximum
            _write_metrics(data, hb=clk() - 15.0)

    def finish(proc, argv, env, logf):
        _write_metrics(data, hb=clk())

    procs = [FakeProc(clk, runtime=None, poll_hook=stepped_clock),
             FakeProc(clk, code=0, runtime=0.0, on_spawn=finish)]
    sup, _, _, _ = _mk_sup(tmp_path, procs, clk, watchdog_sec=10.0)
    assert sup.run() == 0
    assert sup.failures["hang"] == 1 and sup.watchdog_kills == 1
    events, recs = _runlog_events(data)
    kills = [r for r in recs if r["event"] == "watchdog_kill"]
    assert kills[0]["reason"] == "heartbeat moved backwards"


def test_backwards_heartbeat_transient_step_self_heals(tmp_path):
    """A small clock step (shorter than the watchdog window) must NOT
    kill: once the stepped clock catches back up past the old maximum,
    the heartbeat is fresh again."""
    clk = FakeClock()
    data = str(tmp_path / "data")

    def small_step(proc, elapsed):
        if elapsed < 5.0:
            _write_metrics(data, hb=clk())
        else:
            _write_metrics(data, hb=clk() - 3.0)   # catches up at ~8s
        if elapsed >= 15.0:
            proc.returncode = 0

    procs = [FakeProc(clk, runtime=None, poll_hook=small_step)]
    sup, _, _, _ = _mk_sup(tmp_path, procs, clk, watchdog_sec=10.0)
    assert sup.run() == 0
    assert sup.watchdog_kills == 0


# ---------------------------------------------------------------------------
# postmortem stderr tail on failure-class exit records
# ---------------------------------------------------------------------------

def test_crash_exit_record_carries_bounded_stderr_tail(tmp_path):
    from avida_tpu.service.supervisor import STDERR_TAIL_RECORD_BYTES
    clk = FakeClock()
    filler = "x" * 120

    def chatty_crash(proc, argv, env, logf):
        for i in range(64):
            logf.write(f"{filler} line {i}\n")
        logf.write("FATAL: the actual traceback\n")
        logf.flush()

    procs = [FakeProc(clk, code=1, runtime=0.0, on_spawn=chatty_crash),
             FakeProc(clk, code=0, runtime=0.0)]
    sup, data, _, _ = _mk_sup(tmp_path, procs, clk)
    assert sup.run() == 0
    _, recs = _runlog_events(data)
    exits = [r for r in recs if r["event"] == "exit"]
    crash = [r for r in exits if r["class"] == "crash"][0]
    tail = crash["stderr_tail"]
    assert len(tail.encode()) <= STDERR_TAIL_RECORD_BYTES   # bounded
    assert "FATAL: the actual traceback" in tail            # the evidence
    assert "line 0\n" not in tail                           # truncated
    # success exits carry no tail (no failure to explain)
    ok = [r for r in exits if r["class"] == "success"][0]
    assert "stderr_tail" not in ok


# ---------------------------------------------------------------------------
# runlog size-capped rotation
# ---------------------------------------------------------------------------

def test_append_record_rotates_at_cap_mid_append(tmp_path):
    from avida_tpu.observability.runlog import append_record, read_records
    path = str(tmp_path / "log.jsonl")
    recs = [{"record": "supervisor", "i": i, "pad": "p" * 40}
            for i in range(60)]
    for rec in recs:
        append_record(path, rec, max_bytes=600)
    assert os.path.exists(path + ".1")              # rotated mid-append
    assert os.path.getsize(path) <= 600
    assert os.path.getsize(path + ".1") <= 600
    # the rotation pair preserves a contiguous, in-order SUFFIX of the
    # stream (each rotation clobbers the previous .1 aside): the newest
    # record is always present, and both files contribute
    got = [r["i"] for r in read_records(path)]
    assert got == list(range(got[0], 60))
    n_current = len(open(path).readlines())
    assert 0 < n_current < len(got)                 # .1 contributes too


def test_append_record_no_cap_never_rotates(tmp_path):
    from avida_tpu.observability.runlog import append_record
    path = str(tmp_path / "log.jsonl")
    for i in range(50):
        append_record(path, {"i": i})
    assert not os.path.exists(path + ".1")
    assert len(open(path).readlines()) == 50


def test_supervisor_runlog_rotation_is_wired(tmp_path):
    """A long heal loop must not grow supervisor.jsonl without bound:
    TPU_RUNLOG_MAX_BYTES caps it via append_record rotation."""
    clk = FakeClock()
    data = tmp_path / "data"
    ck = tmp_path / "ck"
    os.makedirs(ck, exist_ok=True)
    procs = [FakeProc(clk, code=1, runtime=0.0) for _ in range(9)]
    seq = list(procs)

    def spawn(argv, env, logf):
        proc = seq.pop(0)
        proc._spawned(argv, env, logf)
        return proc

    sup = Supervisor(
        ["-d", str(data), "-set", "TPU_CKPT_DIR", str(ck), "-u", "9"],
        cfg=SupervisorConfig(watchdog_sec=10.0, poll_sec=0.5,
                             grace_sec=30.0, max_retries=8,
                             backoff_base=0.1, backoff_cap=0.2,
                             healthy_sec=1e9),
        env={"TPU_RUNLOG_MAX_BYTES": "2000"}, spawn=spawn,
        clock=clk, sleep=clk.sleep)
    assert sup.runlog_max_bytes == 2000
    assert sup.run() == 1                           # budget exhausted
    assert os.path.exists(str(data / "supervisor.jsonl.1"))
    assert os.path.getsize(str(data / "supervisor.jsonl")) <= 2000


# ---------------------------------------------------------------------------
# --status exit codes (external watchdog contract)
# ---------------------------------------------------------------------------

def test_status_exit_codes(tmp_path, capsys):
    import time as _time

    from avida_tpu.observability.exporter import status_main
    d = str(tmp_path)
    assert status_main(d) == 1                     # no metrics file
    _write_metrics(d, hb=_time.time())
    assert status_main(d) == 0
    assert status_main(d, max_age=60.0) == 0       # fresh
    _write_metrics(d, hb=_time.time() - 120.0)
    assert status_main(d, max_age=60.0) == 2       # stale
    assert "STALE" in capsys.readouterr().out
    assert status_main(d) == 0                     # display-only: no flag
    with open(os.path.join(d, "metrics.prom"), "w") as f:
        f.write("avida_update 3\n")                # heartbeat line missing
    assert status_main(d, max_age=60.0) == 2


def test_status_shows_supervisor_summary(tmp_path, capsys):
    import time as _time

    from avida_tpu.observability.exporter import status_main
    d = str(tmp_path)
    _write_metrics(d, hb=_time.time())
    with open(os.path.join(d, "supervisor.prom"), "w") as f:
        f.write("avida_supervisor_boots_total 3\n"
                'avida_supervisor_failures_total{class="hang"} 2\n'
                "avida_supervisor_retry_budget 6\n")
    assert status_main(d) == 0
    out = capsys.readouterr().out
    assert "supervisor" in out and "boots 3" in out and "failures 2" in out


def test_main_dispatches_status_max_age(tmp_path):
    from avida_tpu.__main__ import main
    assert main(["--status", str(tmp_path)]) == 1
    _write_metrics(str(tmp_path), hb=0.0)          # epoch: maximally stale
    assert main(["--status", str(tmp_path), "--max-age", "60"]) == 2


# ---------------------------------------------------------------------------
# ckpt_tool: torn-manifest reporting + --prune
# ---------------------------------------------------------------------------

def test_ckpt_tool_verify_distinguishes_torn_manifest(tmp_path, capsys):
    base = tmp_path / "ck"
    _gen(base, update=10)
    crc_gen = _gen(base, update=20)
    torn_gen = _gen(base, update=30)
    fi.corrupt_leaf(crc_gen, "merit")
    fi.tear_manifest(torn_gen)
    assert ckpt_tool.main([str(base), "--verify"]) == 0
    out = capsys.readouterr().out
    assert "OK (verified)" in out
    assert "CORRUPT -- " in out and "CRC mismatch" in out
    assert "TORN MANIFEST" in out
    # and torn manifests surface in plain list mode too
    assert ckpt_tool.main([str(base)]) == 0
    assert "TORN MANIFEST" in capsys.readouterr().out


def test_ckpt_tool_verify_all_bad_exits_nonzero(tmp_path, capsys):
    base = tmp_path / "ck"
    fi.tear_manifest(_gen(base, update=10))
    assert ckpt_tool.main([str(base), "--verify"]) == 1


def test_ckpt_tool_prune(tmp_path, capsys):
    base = tmp_path / "ck"
    for u in (10, 20, 30, 40):
        _gen(base, update=u, keep=10)
    for stray in (".tmp-ckpt-000000000099.1",
                  ".old-ckpt-000000000010.2",
                  ".bad-ckpt-000000000020.3"):
        os.makedirs(base / stray)
    removed = ckpt_tool.prune(str(base), keep=2)
    assert len(removed) == 5                       # 3 strays + 2 old gens
    names = sorted(os.path.basename(p)
                   for p in ckpt_mod.list_generations(str(base)))
    assert names == ["ckpt-000000000030", "ckpt-000000000040"]
    assert not [d for d in os.listdir(base) if d.startswith(".")]
    # CLI wrapper prints what it removed and keeps newest regardless
    assert ckpt_tool.main([str(base), "--prune"]) == 0
    assert "generation(s) kept" in capsys.readouterr().out
    # --keep parses as a FLAG (any argument order), never as the base dir
    assert ckpt_tool.main(["--prune", "--keep", "1", str(base)]) == 0
    assert len(ckpt_mod.list_generations(str(base))) == 1
    assert "1 generation(s) kept" in capsys.readouterr().out
    assert ckpt_tool.main([str(base), "--prune", "--keep"]) == 2
    assert "integer argument" in capsys.readouterr().out


def test_ckpt_tool_prune_all_sweeps_a_spool(tmp_path, capsys):
    """`--prune --all SPOOL` sweeps every job's checkpoint debris in
    one pass (fleet spools keep one ck dir per job)."""
    spool = tmp_path / "spool"
    cks = []
    for job in ("j1", "j2", "j3"):
        ck = spool / job / "ck"
        for u in (10, 20, 30):
            _gen(ck, update=u, keep=10)
        os.makedirs(ck / f".tmp-ckpt-000000000099.{job}")
        os.makedirs(ck / f".bad-ckpt-000000000010.{job}")
        cks.append(ck)
    # spool clutter that is NOT a checkpoint dir must be untouched
    (spool / "j1" / "data").mkdir()
    (spool / "j1" / "data" / "metrics.prom").write_text("x 1\n")
    # a JOB merely named ckpt-something must not make the spool root
    # look like a checkpoint dir (its whole fault domain would be
    # rmtree'd as retention overflow)
    (spool / "ckpt-seedjob" / "ck").mkdir(parents=True)
    (spool / "ckpt-seedjob" / "keep.txt").write_text("precious\n")
    swept = ckpt_tool.prune_all(str(spool), keep=2)
    assert str(spool) not in swept
    assert os.path.exists(spool / "ckpt-seedjob" / "keep.txt")
    assert sorted(swept) == [str(ck) for ck in cks]
    for ck in cks:
        removed = swept[str(ck)]
        assert len(removed) == 3                   # 2 strays + 1 old gen
        names = [os.path.basename(p)
                 for p in ckpt_mod.list_generations(str(ck))]
        assert names == ["ckpt-000000000020", "ckpt-000000000030"]
        assert not [d for d in os.listdir(ck) if d.startswith(".")]
    assert os.path.exists(spool / "j1" / "data" / "metrics.prom")
    # CLI plumbing: --prune --all with --keep, order-insensitive
    for u in (40, 50, 60):
        _gen(cks[0], update=u, keep=10)
    assert ckpt_tool.main(["--prune", "--all", str(spool),
                           "--keep", "1"]) == 0
    out = capsys.readouterr().out
    assert "checkpoint dir(s)" in out
    assert len(ckpt_mod.list_generations(str(cks[0]))) == 1
    assert ckpt_tool.main(["--all", str(spool)]) == 2   # needs --prune


def _aside(base, update=10):
    """Simulate a crash inside write_generation's publish window: a
    generation moved aside, nothing renamed in to replace it."""
    gen = _gen(base, update=update)
    aside = str(base / f".old-ckpt-{update:012d}.77")
    os.rename(gen, aside)
    return aside


def test_prune_never_deletes_the_only_resumable_aside(tmp_path):
    """An `.old-*` publish aside can be the ONLY resumable copy (crash
    inside write_generation's two-rename window) -- prune must keep it
    until a published generation verifies."""
    base = tmp_path / "ck"
    aside = _aside(base)
    assert ckpt_mod.restore_candidates(str(base)) == [aside]
    removed = ckpt_tool.prune(str(base), keep=2)
    assert removed == [] and os.path.isdir(aside)  # rescue copy kept

    # a corrupt published generation is not good enough either
    base2 = tmp_path / "ck2"
    bad = _gen(base2, update=20)
    fi.tear_manifest(bad)
    aside2 = _aside(base2, update=10)
    assert aside2 not in ckpt_tool.prune(str(base2), keep=2)
    assert os.path.isdir(aside2)

    # once a published generation VERIFIES, the aside is debris
    base3 = tmp_path / "ck3"
    _gen(base3, update=20)
    aside3 = _aside(base3, update=10)
    assert aside3 in ckpt_tool.prune(str(base3), keep=2)
    assert not os.path.isdir(aside3)


def test_prune_retention_never_removes_newest_valid_generation(tmp_path):
    """Bit-rotted newer generations must not push the only resumable
    one out of the retention window."""
    base = tmp_path / "ck"
    good = _gen(base, update=4, keep=10)
    for u in (8, 12):
        fi.tear_manifest(_gen(base, update=u, keep=10))
    removed = ckpt_tool.prune(str(base), keep=2)
    assert good not in removed and os.path.isdir(good)
    path, manifest = ckpt_mod.latest_valid(str(base))
    assert manifest["update"] == 4                 # still resumable


def test_sigterm_during_backoff_exits_before_next_boot(tmp_path):
    """Preemption that lands mid-backoff (no child alive) must stop the
    supervisor within the sleep, not after one more full boot."""
    clk = FakeClock()
    procs = [FakeProc(clk, code=1, runtime=0.0) for _ in range(3)]
    sup, data, _, _ = _mk_sup(tmp_path, procs, clk,
                              backoff_base=5.0, backoff_cap=10.0)
    real_sleep = sup._sleep

    def preempting_sleep(s):
        real_sleep(s)
        sup._stop = True                           # SIGTERM mid-backoff

    sup._sleep = preempting_sleep
    assert sup.run() == 0
    assert sup.boots == 1                          # no further boot
    events, _ = _runlog_events(data)
    assert "supervisor_preempted" in events


def test_explicit_config_off_overrides_fault_env(monkeypatch):
    """`-set TPU_FAULT 0` must defuse a fault exported in the shell;
    only an ABSENT config value falls through to the environment."""
    from avida_tpu.config import AvidaConfig
    monkeypatch.setenv("TPU_FAULT", "crash@chunk=1")
    cfg = AvidaConfig()
    assert fi.active_spec(cfg) == "crash@chunk=1"  # absent -> env
    for off in ("0", "-", ""):
        cfg.set("TPU_FAULT", off)
        assert fi.active_spec(cfg) is None         # explicit off wins
    cfg.set("TPU_FAULT", "sigkill@chunk=2")
    assert fi.active_spec(cfg) == "sigkill@chunk=2"


def test_render_families_labeled_and_scalar():
    from avida_tpu.observability.exporter import read_metrics, render_families
    text = render_families([
        ("x_total", "counter", "things", 3),
        ("y_total", "counter", "classified things",
         {'class="a"': 1, 'class="b"': 2}),
    ])
    assert "# TYPE x_total counter" in text
    assert 'y_total{class="a"} 1' in text
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "m.prom")
        with open(p, "w") as f:
            f.write(text)
        m = read_metrics(p)
    assert m["x_total"] == 3 and m['y_total{class="b"}'] == 2
