"""Systematics (genotype arbiter) unit tests + world integration.

Models the reference's provenance semantics (GenotypeArbiter.cc:79-123):
dedup by sequence, parent links, depth, extinction bookkeeping.
"""

import numpy as np

from avida_tpu.systematics import GenotypeArbiter


def g(*ops):
    return np.asarray(ops, np.int8)


def test_seed_and_dedup():
    arb = GenotypeArbiter(16)
    arb.classify_seed(0, g(1, 2, 3))
    arb.classify_seed(1, g(1, 2, 3))
    arb.classify_seed(2, g(1, 2, 4))
    assert arb.num_genotypes == 2
    dom = arb.dominant()
    assert dom.num_units == 2 and dom.total_units == 2


def test_birth_parent_links_and_depth():
    arb = GenotypeArbiter(16)
    arb.classify_seed(0, g(1, 2, 3))
    alive = np.zeros(16, bool)
    alive[[0, 1]] = True
    # child in cell 1 with a mutated genome, parent cell 0
    arb.process(update=5, alive=alive,
                newborn_cells=np.asarray([1]),
                newborn_genomes=np.asarray([[9, 2, 3, 0]], np.int8),
                newborn_lens=np.asarray([3]),
                parent_cells=np.asarray([0]))
    assert arb.num_genotypes == 2
    child = arb.genotypes[arb.cell_gid[1]]
    parent = arb.genotypes[arb.cell_gid[0]]
    assert child.parent_gid == parent.gid
    assert child.depth == 1
    assert child.update_born == 5


def test_death_and_extinction():
    arb = GenotypeArbiter(8)
    arb.classify_seed(0, g(1, 1))
    alive = np.zeros(8, bool)  # everyone died
    arb.process(update=3, alive=alive,
                newborn_cells=np.asarray([], int),
                newborn_genomes=np.zeros((0, 4), np.int8),
                newborn_lens=np.asarray([], int),
                parent_cells=np.asarray([], int))
    assert arb.num_genotypes == 0
    extinct = next(iter(arb.genotypes.values()))
    assert extinct.update_deactivated == 3


def test_same_genome_rebirth_reactivates():
    arb = GenotypeArbiter(8)
    arb.classify_seed(0, g(5, 5))
    gid = arb.cell_gid[0]
    alive = np.zeros(8, bool)
    alive[1] = True
    arb.process(update=2, alive=alive,
                newborn_cells=np.asarray([1]),
                newborn_genomes=np.asarray([[5, 5]], np.int8),
                newborn_lens=np.asarray([2]),
                parent_cells=np.asarray([0]))
    # cell 0 died, cell 1 carries the same genotype: still one genotype, live
    assert arb.cell_gid[1] == gid
    assert arb.genotypes[gid].num_units == 1
    assert arb.genotypes[gid].update_deactivated == -1


def test_world_integration_systematics(small_world_cfg):
    from avida_tpu.world import World
    w = World(cfg=small_world_cfg.copy())
    w.inject()
    for _ in range(40):
        w.run_update()
        w.update += 1
    sysm = w.systematics
    assert sysm is not None
    # live genotype units must agree with the alive mask
    n_alive = int(np.asarray(w.state.alive).sum())
    live_units = sum(gg.num_units for gg in sysm.genotypes.values())
    assert live_units == n_alive
    if n_alive > 1:
        assert sysm.num_births_total > 1


def test_prune_extinct_keeps_live_ancestry():
    arb = GenotypeArbiter(8)
    arb.classify_seed(0, g(1,))
    alive = np.zeros(8, bool)
    alive[1] = True
    arb.process(update=1, alive=alive,
                newborn_cells=np.asarray([1]),
                newborn_genomes=np.asarray([[2]], np.int8),
                newborn_lens=np.asarray([1]),
                parent_cells=np.asarray([0]))
    root_gid = 1
    arb.prune_extinct(keep_ancestry=True)
    assert root_gid in arb.genotypes  # extinct but ancestral to live genotype
