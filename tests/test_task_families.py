"""Task families beyond logic-9: full 3-input logic set + math family.

Reference: cTaskLib.cc:87-260 -- 215 registrations; the logic families
(all 68 3-input functions) evaluate via logic-ID membership, the math
families via arithmetic-candidate matching (Task_Math{1,2,3}in_*).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from avida_tpu.config.environment import (LOGIC_TASKS, Environment, Reaction,
                                          Process, load_environment)
from avida_tpu.ops import tasks as tasks_ops


def test_full_logic_family_loads():
    # all 68 3-input functions present
    three_in = [k for k in LOGIC_TASKS if k.startswith("logic_3")
                and not k.endswith("_dup")]
    assert len(three_in) == 68
    # spot checks against the reference constants (cTaskLib.cc)
    assert LOGIC_TASKS["logic_3AH"] == (128,)     # A&B&C
    assert LOGIC_TASKS["logic_3AN"] == (254,)     # A|B|C
    assert LOGIC_TASKS["logic_3CP"] == (174, 186, 206, 220, 242, 244)


def test_reference_style_environment_loads(tmp_path):
    cfg = tmp_path / "environment.cfg"
    cfg.write_text(
        "REACTION NOT not process:value=1.0:type=pow\n"
        "REACTION L3AH logic_3AH process:value=4.0:type=pow\n"
        "REACTION M1AA math_1AA process:value=2.0:type=pow\n"
        "REACTION M2AN math_2AN process:value=3.0:type=pow\n")
    env = load_environment(str(cfg))
    tables = env.device_tables()
    assert tables["task_math_name"] == ("", "", "math_1AA", "math_2AN")
    assert tables["task_logic_mask"][1, 128]      # logic_3AH id

def test_math_performed_matches_candidates():
    ib = jnp.asarray([[7, 3, 0], [10, 4, 2], [5, 5, 5]], jnp.int32)
    ibn = jnp.asarray([2, 3, 3], jnp.int32)
    # math_1AA (2X): outputs 14 (=2*7), 9 (no), 10 (=2*5)
    out = jnp.asarray([14, 9, 10], jnp.int32)
    hit = np.asarray(tasks_ops.math_performed("math_1AA", ib, ibn, out))
    assert hit.tolist() == [True, False, True]
    # math_2AN (X+Y): 10=7+3 yes; 14=10+4 yes; 10=5+5 yes
    out2 = jnp.asarray([10, 14, 10], jnp.int32)
    hit2 = np.asarray(tasks_ops.math_performed("math_2AN", ib, ibn, out2))
    assert hit2.tolist() == [True, True, True]
    # math_3AH (X+Y+Z): needs 3 inputs -> row 0 (only 2 stored) can't match
    out3 = jnp.asarray([10, 16, 15], jnp.int32)
    hit3 = np.asarray(tasks_ops.math_performed("math_3AH", ib, ibn, out3))
    assert hit3.tolist() == [False, True, True]
    # math_2AC (X%Y): 7%3=1
    out4 = jnp.asarray([1, 2, 0], jnp.int32)
    hit4 = np.asarray(tasks_ops.math_performed("math_2AC", ib, ibn, out4))
    assert bool(hit4[0]) and bool(hit4[2])


def test_math_reaction_rewards_bonus():
    """An organism outputting 2*input gets the math_1AA pow bonus through
    the full reaction pipeline."""
    env = Environment(reactions=[
        Reaction("M1AA", "math_1AA", [Process(value=2.0, type=2)], []),
    ])
    from avida_tpu.config import AvidaConfig
    from avida_tpu.core.state import make_world_params
    from avida_tpu.config.instset import default_instset
    cfg = AvidaConfig()
    cfg.WORLD_X = cfg.WORLD_Y = 2
    params = make_world_params(cfg, default_instset(), env)
    tables = tasks_ops.env_tables_to_device(params)
    n = 4
    ib = jnp.asarray([[6, 0, 0]] * n, jnp.int32)
    ibn = jnp.full(n, 1, jnp.int32)
    out = jnp.asarray([12, 11, 12, 12], jnp.int32)
    io = jnp.asarray([True, True, False, True])
    logic_id = tasks_ops.compute_logic_id(ib, ibn, out)
    bonus, tc, rc, _, _, _, any_r = tasks_ops.apply_reactions(
        params, tables, io, logic_id, jnp.ones(n, jnp.float32),
        jnp.zeros((n, 1), jnp.int32), jnp.zeros((n, 1), jnp.int32),
        jnp.zeros(0), jnp.zeros((0, n)),
        input_buf=ib, input_buf_n=ibn, output=out)
    got = np.asarray(bonus)
    assert got[0] == 4.0      # 2^2 pow bonus
    assert got[1] == 1.0      # wrong output
    assert got[2] == 1.0      # no IO
    assert got[3] == 4.0
