"""Task evaluation tests: the vectorized logic-ID computation must agree with
cTaskLib::SetupTests semantics (cTaskLib.cc:369-448) on known cases."""

import jax.numpy as jnp
import numpy as np

from avida_tpu.config.environment import LOGIC_TASKS
from avida_tpu.ops.tasks import compute_logic_id

# The deterministic all-combination inputs from cEnvironment::SetupInputs
# (cEnvironment.cc:1286-1289)
I0, I1, I2 = 0x0F13149F, 0x3308E53E, 0x556241EB


def _i32(v):
    v = int(v) & 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


def lid(inputs, out):
    buf = np.zeros((1, 3), np.int32)
    n = len(inputs)
    for i, v in enumerate(inputs):
        buf[0, i] = _i32(v)
    return int(compute_logic_id(jnp.asarray(buf), jnp.asarray([n]),
                                jnp.asarray([_i32(out)]))[0])


def test_not_single_input():
    # output = ~input with one input stored: logic table ~A duplicated -> 85
    assert lid([I0], ~I0) in LOGIC_TASKS["not"]


def test_not_three_inputs():
    # most recent input is buf[0]; ~buf[0] is still a NOT id
    assert lid([I0, I1, I2], ~I0) in LOGIC_TASKS["not"]
    assert lid([I2, I1, I0], ~I2) in LOGIC_TASKS["not"]


def test_nand_and_or():
    assert lid([I0, I1], ~(I0 & I1)) in LOGIC_TASKS["nand"]
    assert lid([I0, I1, I2], I0 & I1) in LOGIC_TASKS["and"]
    assert lid([I0, I1, I2], I1 | I2) in LOGIC_TASKS["or"]
    assert lid([I0, I1, I2], I0 ^ I1) in LOGIC_TASKS["xor"]
    assert lid([I0, I1, I2], ~(I0 ^ I2)) in LOGIC_TASKS["equ"]
    assert lid([I0, I1, I2], ~(I0 | I1)) in LOGIC_TASKS["nor"]
    assert lid([I0, I1, I2], I0 & ~I1) in LOGIC_TASKS["andn"]
    assert lid([I0, I1, I2], I0 | ~I1) in LOGIC_TASKS["orn"]


def test_echo():
    assert lid([I0, I1, I2], I1) in LOGIC_TASKS["echo"]


def test_inconsistent_output():
    # A random constant is (almost surely) not a pure function of the inputs
    assert lid([I0, I1, I2], 0x12345678) == -1


def test_no_inputs_yields_constant_tables():
    # With zero inputs stored the output must be constant 0 or ~0 to be a
    # function; anything else is inconsistent
    assert lid([], 0) == 0
    assert lid([], -1) == 255
    assert lid([], 42) == -1


def test_logic_id_disjoint_sets():
    names = ["not", "nand", "and", "orn", "or", "andn", "nor", "xor", "equ"]
    seen = {}
    for n in names:
        for v in LOGIC_TASKS[n]:
            assert v not in seen, f"{n} and {seen.get(v)} share id {v}"
            seen[v] = n
