"""Telemetry subsystem (avida_tpu/observability/).

Three guarantees:
  1. zero-cost when disabled -- the update program traces to the
     identical jaxpr whether or not telemetry machinery has been
     imported/used, and a disabled World writes no telemetry files;
  2. the phase-fenced staged path is bit-identical to the fused
     update_step (same keys -> same trajectory);
  3. enabled-path counters reconcile EXACTLY with the .dat outputs of
     the same run, and phase durations account for the update wall time.

The zero-cost-when-disabled guards run in the fast tier; the
enabled-path smoke run (50 telemetry updates + .dat reconciliation) is
marked slow.
"""

from __future__ import annotations

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from avida_tpu.config import AvidaConfig
from avida_tpu.config.environment import default_logic9_environment
from avida_tpu.config.instset import default_instset
from avida_tpu.config.events import parse_event_line
from avida_tpu.core.state import make_world_params, zeros_population
from avida_tpu.ops import birth as birth_ops
from avida_tpu.ops.update import update_step
from avida_tpu.world import World


def _small_setup():
    cfg = AvidaConfig()
    cfg.WORLD_X = 6
    cfg.WORLD_Y = 6
    cfg.TPU_MAX_MEMORY = 64
    p = make_world_params(cfg, default_instset(),
                          default_logic9_environment())
    st = zeros_population(p.num_cells, p.max_memory, p.num_reactions)
    nb = jnp.asarray(birth_ops.neighbor_table(6, 6, p.geometry))
    return p, st, nb


def _trace_update(p, st, nb):
    return str(jax.make_jaxpr(
        lambda s, k, u: update_step(p, s, k, nb, u))(
            st, jax.random.key(0), jnp.int32(0)))


def test_disabled_jaxpr_unchanged_by_telemetry():
    """The production update program must be unaffected by the telemetry
    code paths: tracing it before and after building/running a
    counter-collecting staged update yields the same jaxpr, and the
    counter-threaded interpret phase demonstrably traces DIFFERENT code
    (so the equality is not vacuous)."""
    from avida_tpu.observability import StagedUpdate, Timeline, dispatch_init
    from avida_tpu.ops.update import (interpret_phase, schedule_phase,
                                      static_cap)

    p, st, nb = _small_setup()
    jx_before = _trace_update(p, st, nb)

    # exercise the telemetry machinery: a full staged update with the
    # dispatch-mix accumulator threaded through the while_loop
    staged = StagedUpdate(p, nb, collect_dispatch=True)
    st2, executed, dispatch, granted, _ = staged.run(
        st, jax.random.key(1), 0, Timeline())
    assert dispatch is not None and dispatch.shape[0] == p.num_insts

    jx_after = _trace_update(p, st, nb)
    assert jx_before == jx_after

    # the counters carry really changes the traced program
    def interp(st, k):
        budgets, granted, max_k = schedule_phase(p, st, k)
        return interpret_phase(p, st, k, granted, max_k, static_cap(p),
                               dispatch_init(p))

    def interp_plain(st, k):
        budgets, granted, max_k = schedule_phase(p, st, k)
        return interpret_phase(p, st, k, granted, max_k, static_cap(p))

    jx_counted = str(jax.make_jaxpr(interp)(st, jax.random.key(0)))
    jx_plain = str(jax.make_jaxpr(interp_plain)(st, jax.random.key(0)))
    assert jx_counted != jx_plain


def test_disabled_world_writes_no_telemetry_files(tmp_path):
    cfg = AvidaConfig()
    cfg.WORLD_X = 6
    cfg.WORLD_Y = 6
    cfg.TPU_MAX_MEMORY = 320
    cfg.RANDOM_SEED = 3
    cfg.AVE_TIME_SLICE = 5
    w = World(cfg=cfg, data_dir=str(tmp_path))
    assert w.telemetry is None
    w.events = [parse_event_line("u begin Inject")]
    w.run(max_updates=2)
    names = os.listdir(tmp_path)
    assert "telemetry.jsonl" not in names
    assert not any("profile" in n for n in names)


@pytest.mark.slow
def test_staged_update_bit_identical_to_fused():
    """StagedUpdate (phase-fenced jits) must reproduce the fused
    update_step trajectory exactly -- same phases, same order, same
    keys."""
    from avida_tpu.observability import StagedUpdate, Timeline

    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.TPU_MAX_MEMORY = 320
    cfg.RANDOM_SEED = 11
    cfg.AVE_TIME_SLICE = 20
    w = World(cfg=cfg)
    w.inject()
    st_f = st_s = w.state
    staged = StagedUpdate(w.params, w.neighbors)
    tl = Timeline()
    for u in range(3):
        k = jax.random.fold_in(w._run_key, u)
        st_f, ex_f = update_step(w.params, st_f, k, w.neighbors,
                                 jnp.int32(u))
        st_s, ex_s, dispatch, _, _ = staged.run(st_s, k, u, tl)
        assert int(ex_f) == int(ex_s)
        # on the single-thread XLA path the dispatch mix sums to the
        # executed count (insts_executed charges once per scheduled cycle)
        assert int(dispatch.sum()) == int(ex_s)
    for a, b in zip(jax.tree_util.tree_leaves(st_f),
                    jax.tree_util.tree_leaves(st_s)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.fixture(scope="module")
def telemetry_run(tmp_path_factory):
    """One 50-update telemetry-enabled smoke run with every-update .dat
    prints, shared by the reconciliation tests."""
    data_dir = str(tmp_path_factory.mktemp("teldata"))
    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.TPU_MAX_MEMORY = 320
    cfg.RANDOM_SEED = 42
    cfg.AVE_TIME_SLICE = 30
    cfg.TPU_TELEMETRY = 1
    w = World(cfg=cfg, data_dir=data_dir)
    w.events = [parse_event_line("u begin Inject"),
                parse_event_line("u 0:1:end PrintCountData"),
                parse_event_line("u 0:1:end PrintTasksExeData")]
    w.run(max_updates=50)
    lines = [json.loads(l) for l in
             open(os.path.join(data_dir, "telemetry.jsonl"))]
    meta = [l for l in lines if l["record"] == "meta"]
    recs = [l for l in lines if l["record"] == "update"]
    return data_dir, meta, recs


def _dat_rows(path):
    rows = []
    for line in open(path):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        rows.append([float(x) for x in line.split()])
    return rows


@pytest.mark.slow
def test_telemetry_meta_and_shape(telemetry_run):
    data_dir, meta, recs = telemetry_run
    assert len(meta) == 1
    m = meta[0]
    assert m["num_cells"] == 64
    assert m["interpret_path"] in ("pallas", "xla_while_loop")
    assert len(m["inst_names"]) > 20
    assert len(recs) == 50
    assert [r["update"] for r in recs] == list(range(50))


@pytest.mark.slow
def test_counters_match_dat_outputs(telemetry_run):
    """Acceptance: per-update counters (births, instructions executed,
    task triggers) match the corresponding .dat outputs EXACTLY.  The
    count.dat row printed at update u+1 covers update u's work (events
    fire before the update runs)."""
    data_dir, _, recs = telemetry_run
    count = {int(r[0]): r for r in
             _dat_rows(os.path.join(data_dir, "count.dat"))}
    tasks_exe = {int(r[0]): r for r in
                 _dat_rows(os.path.join(data_dir, "tasks_exe.dat"))}
    checked = 0
    for r in recs:
        u = r["update"]
        row = count.get(u + 1)
        if row is None:          # the print after the last update never fires
            continue
        c = r["counters"]
        assert int(row[1]) == c["executed"], (u, row[1], c["executed"])
        assert int(row[8]) == c["births"], (u, row[8], c["births"])
        te = tasks_exe.get(u + 1)
        assert [int(x) for x in te[1:]] == c["task_triggers"], u
        # dispatch mix sums to the executed count on the XLA path
        if "dispatch_mix" in c:
            assert sum(c["dispatch_mix"]) == c["executed"]
        checked += 1
    assert checked >= 45
    # the run must actually have had activity worth reconciling
    assert sum(r["counters"]["executed"] for r in recs) > 0
    assert sum(r["counters"]["births"] for r in recs) > 0


@pytest.mark.slow
def test_phase_durations_cover_wall_time(telemetry_run):
    """Acceptance: per-update phase durations sum to within 10% of the
    measured update wall time (aggregate over the run; individual updates
    can be skewed by GC pauses between brackets)."""
    _, _, recs = telemetry_run
    # skip the first records (jit compilation dominates them)
    body = recs[5:]
    tot_phases = sum(sum(r["phases"].values()) for r in body)
    tot_wall = sum(r["wall_ms"] for r in body)
    assert tot_wall > 0
    ratio = tot_phases / tot_wall
    assert 0.9 <= ratio <= 1.02, ratio
    # the interpret phase must be visible and dominant-or-substantial,
    # exposing the kernel vs pack/flush split ROUND5_NOTES.md asks for
    keys = set().union(*(r["phases"].keys() for r in body))
    assert ("while_loop" in keys) or {"pack", "kernel", "unpack"} <= keys
    assert "birth_flush" in keys and "schedule" in keys


@pytest.mark.slow
def test_budget_tail_counters(telemetry_run):
    _, meta, recs = telemetry_run
    block = meta[0]["budget_block"]
    assert block >= 1
    for r in recs:
        b = r["counters"]["budget"]
        assert b["ceiling"] >= b["granted"] >= 0
        assert 0.0 <= b["utilization"] <= 1.0
        # the loop can only execute granted cycles or fewer (stalls)
        assert r["counters"]["executed"] <= b["granted"]


def test_budget_tail_math():
    from avida_tpu.observability import budget_tail
    g = jnp.asarray([1, 2, 3, 4, 10, 0, 0, 0], jnp.int32)
    t = budget_tail(g, 4)
    assert int(t["granted_sum"]) == 20
    # blocks [1,2,3,4] and [10,0,0,0] -> ceilings 4*4 + 10*4 = 56
    assert int(t["ceiling_sum"]) == 56
    assert int(t["block_max_max"]) == 10


@pytest.mark.slow
def test_profile_phases_harness():
    """The unified harness (replacing scripts/profile_update.py) returns a
    per-phase breakdown whose phases are all positive."""
    from avida_tpu.observability import profile_phases

    cfg = AvidaConfig()
    cfg.WORLD_X = 6
    cfg.WORLD_Y = 6
    cfg.TPU_MAX_MEMORY = 320
    cfg.RANDOM_SEED = 5
    cfg.AVE_TIME_SLICE = 10
    w = World(cfg=cfg)
    w.inject()
    phases, st, granted = profile_phases(
        w.params, w.state, w.neighbors, jax.random.key(0), reps=2, warmup=1)
    assert granted > 0
    for name in ("schedule", "birth_flush"):
        assert phases.get(name, 0) > 0, phases
    assert ("while_loop" in phases) or ("kernel" in phases)
    # the retired script must stay retired (its caveats live in the
    # harness docstring now)
    assert not os.path.exists(
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "profile_update.py"))
