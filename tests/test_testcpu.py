"""Batched Test CPU tests (avida_tpu/analyze/testcpu.py).

Oracle: the default ancestor's known life history (gestation 389, merit 97,
fitness 97/389 -- reference golden data, tests/heads_default_100u) and
obvious non-replicators.
"""

import numpy as np
import pytest

from avida_tpu.analyze import evaluate_genomes
from avida_tpu.config import AvidaConfig, default_instset
from avida_tpu.config.environment import default_logic9_environment
from avida_tpu.core.state import make_world_params
from avida_tpu.world import default_ancestor

pytestmark = pytest.mark.slow


def make_params(L=320):
    cfg = AvidaConfig()
    cfg.WORLD_X = 1
    cfg.WORLD_Y = 1
    cfg.TPU_MAX_MEMORY = L
    return make_world_params(cfg, default_instset(), default_logic9_environment())


def pad(g, L):
    out = np.zeros(L, np.int8)
    out[:len(g)] = g
    return out


def test_ancestor_metrics():
    params = make_params()
    iset = default_instset()
    anc = default_ancestor(iset)
    junk = np.full(100, iset.inst_names.index("nop-C"), np.int8)  # all nops
    genomes = np.stack([pad(anc, 320), pad(junk, 320)])
    lens = np.asarray([len(anc), 100], np.int32)
    r = evaluate_genomes(params, genomes, lens)
    assert bool(r.viable[0])
    assert int(r.gestation_time[0]) == 389
    assert float(r.merit[0]) == 97.0
    assert float(r.fitness[0]) == pytest.approx(97.0 / 389.0)
    assert int(r.offspring_len[0]) == 100
    np.testing.assert_array_equal(r.offspring_genome[0, :100], anc)
    assert int(r.generations[0]) == 0          # breeds true in generation 1
    # the nop ball never divides
    assert not bool(r.viable[1])


def test_mutations_disabled_in_sandbox():
    """The sandbox must evaluate the genotype deterministically even when the
    world config has mutations on (ref cTestCPU uses its own rate context)."""
    params = make_params()  # stock COPY_MUT_PROB=0.0075 active in world runs
    anc = default_ancestor(default_instset())
    genomes = np.stack([pad(anc, 320)] * 4)
    lens = np.full(4, len(anc), np.int32)
    r = evaluate_genomes(params, genomes, lens, seed=123)
    for i in range(4):
        np.testing.assert_array_equal(r.offspring_genome[i, :100], anc)
    assert (r.gestation_time == 389).all()


def test_nonviable_knockout():
    """Knocking the divide out of the ancestor must make it non-viable --
    the ANALYZE_KNOCKOUTS primitive (cAnalyze.cc)."""
    params = make_params()
    iset = default_instset()
    anc = default_ancestor(iset)
    ko = anc.copy()
    ko[96] = iset.inst_names.index("nop-C")    # h-divide -> nop-C
    genomes = np.stack([pad(anc, 320), pad(ko, 320)])
    lens = np.full(2, len(anc), np.int32)
    r = evaluate_genomes(params, genomes, lens)
    assert bool(r.viable[0])
    assert not bool(r.viable[1])
