"""Intra-organism threads: fork-th / kill-th / id-th +
THREAD_SLICING_METHOD (ref cHardwareCPU.cc:346-351, ForkThread cc:1505,
KillThread cc:1592, SingleProcess thread loop cc:930-948,
cAvidaConfig.h:558-564)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from avida_tpu.config import AvidaConfig
from avida_tpu.config.instset import default_instset
from avida_tpu.config.environment import default_logic9_environment
from avida_tpu.core.state import make_world_params, zeros_population
from avida_tpu.ops.interpreter import micro_step, micro_step_threads


def _thread_instset():
    s = default_instset()
    for name in ("fork-th", "kill-th", "id-th"):
        s.inst_names.append(name)
        s.redundancy = np.append(s.redundancy, 1.0)
        s.cost = np.append(s.cost, 0).astype(np.int32)
        s.ft_cost = np.append(s.ft_cost, 0).astype(np.int32)
        s.energy_cost = np.append(s.energy_cost, 0.0)
        s.prob_fail = np.append(s.prob_fail, 0.0)
        s.addl_time_cost = np.append(s.addl_time_cost, 0).astype(np.int32)
        s.res_cost = np.append(s.res_cost, 0.0)
    return s


def _params(max_threads=2, slicing=0):
    cfg = AvidaConfig()
    cfg.WORLD_X = 2
    cfg.WORLD_Y = 2
    cfg.TPU_MAX_MEMORY = 64
    cfg.MAX_CPU_THREADS = max_threads
    cfg.THREAD_SLICING_METHOD = slicing
    cfg.COPY_MUT_PROB = 0.0
    return make_world_params(cfg, _thread_instset(),
                             default_logic9_environment())


def _one_org(params, program):
    n, L, R = params.num_cells, params.max_memory, params.num_reactions
    st = zeros_population(n, L, R, max_threads=params.max_cpu_threads)
    tape = np.zeros((n, L), np.uint8)
    tape[0, : len(program)] = program
    return st.replace(
        tape=jnp.asarray(tape),
        mem_len=st.mem_len.at[0].set(len(program)),
        genome_len=st.genome_len.at[0].set(len(program)),
        alive=st.alive.at[0].set(True))


def _step_fn(params):
    if params.max_cpu_threads > 1:
        return micro_step_threads
    return micro_step


def _run(params, st, cycles, seed=0):
    mask = jnp.zeros(params.num_cells, bool).at[0].set(True)
    fn = _step_fn(params)
    step = jax.jit(lambda s, k: fn(params, s, k, mask))
    key = jax.random.key(seed)
    for c in range(cycles):
        key, k = jax.random.split(key)
        st = step(st, k)
    return st


def test_fork_spawns_thread_and_both_run():
    """fork-th at position 0: the child resumes at 1, the parent at 2
    (Inst_ForkThread's manual Advance + the end-of-cycle advance); under
    THREAD_SLICING_METHOD 0 round-robin, both threads execute their own
    instruction stream."""
    p = _params(max_threads=2, slicing=0)
    s = _thread_instset()
    fork, inc, dec = s.opcode("fork-th"), s.opcode("inc"), s.opcode("dec")
    nopA = s.opcode("nop-A")
    # 0:fork, 1:inc (child starts here), 2:dec (parent resumes here)
    st = _one_org(p, [fork, inc, dec, nopA, nopA, nopA, nopA, nopA])
    st = _run(p, st, 1)
    assert bool(st.t_alive[0, 0])                  # thread spawned
    assert int(st.t_heads[0, 0, 0]) == 1           # child IP at fork+1
    assert int(st.heads[0, 0]) == 2                # parent IP at fork+2
    assert int(st.t_ids[0, 0]) == 1                # lowest free id

    # two more cycles of round-robin: child runs inc (BX+1), parent dec
    st = _run(p, st, 2, seed=9)
    # child (slot 1) executed tape[1]=inc (followed by dec, not a nop:
    # default ?BX?): its BX == +1
    assert int(st.t_regs[0, 0, 1]) == 1, np.asarray(st.t_regs[0])
    # parent (slot 0) executed tape[2]=dec with the trailing nop-A
    # modifier: its AX == -1
    assert int(st.regs[0, 0]) == -1, np.asarray(st.regs[0])


def test_fork_fails_at_cap_but_ip_still_skips():
    """At MAX_CPU_THREADS=1 fork-th fails (no slot) yet the IP still
    advances by 2 (the manual Advance precedes the failure check)."""
    p = _params(max_threads=1)
    s = _thread_instset()
    fork, inc = s.opcode("fork-th"), s.opcode("inc")
    st = _one_org(p, [fork, inc, inc, inc])
    st = _run(p, st, 1)
    assert int(st.heads[0, 0]) == 2
    assert int(st.regs[0].sum()) == 0


def test_kill_thread_and_id_th():
    """kill-th from the forked thread frees its slot; id-th reports
    distinct ids per thread."""
    p = _params(max_threads=2, slicing=0)
    s = _thread_instset()
    fork, kill, idth = (s.opcode("fork-th"), s.opcode("kill-th"),
                        s.opcode("id-th"))
    nopA = s.opcode("nop-A")
    # 0:fork -> child at 1 (kill-th: child dies), parent at 2 (id-th)
    st = _one_org(p, [fork, kill, idth, nopA, nopA, nopA, nopA, nopA])
    st = _run(p, st, 1)          # fork
    assert bool(st.t_alive[0, 0])
    st = _run(p, st, 1, seed=5)  # round-robin -> child executes kill-th
    assert not bool(st.t_alive[0, 0])
    st = _run(p, st, 1, seed=6)  # parent executes id-th -> BX = 0
    assert int(st.regs[0, 1]) == 0
    # kill-th with a single thread fails silently
    st2 = _one_org(p, [kill, idth, nopA, nopA, nopA, nopA, nopA, nopA])
    st2 = _run(p, st2, 1)
    assert int(st2.heads[0, 0]) == 1


def test_slicing_method_1_runs_all_threads_per_cycle():
    """THREAD_SLICING_METHOD 1: every live thread executes each scheduler
    cycle, but time_used advances once per cycle (cc:930-948)."""
    p = _params(max_threads=2, slicing=1)
    s = _thread_instset()
    fork, inc, dec = s.opcode("fork-th"), s.opcode("inc"), s.opcode("dec")
    nopA = s.opcode("nop-A")
    st = _one_org(p, [fork, inc, dec, nopA, nopA, nopA, nopA, nopA])
    st = _run(p, st, 1)          # cycle 1: only thread 0 exists: fork
    st = _run(p, st, 1, seed=3)  # cycle 2: BOTH threads run one inst
    assert int(st.t_regs[0, 0, 1]) == 1    # child ran inc (?BX?)
    assert int(st.regs[0, 0]) == -1        # parent ran dec ?AX? (nop-A mod)
    assert int(st.time_used[0]) == 2       # one charge per cycle


def test_slicing_method_1_fork_waits_for_next_slice():
    """Fork timing under THREAD_SLICING_METHOD 1: the per-lane live-thread
    count is fixed BEFORE the sub-step loop (num_inst_exec at
    cHardwareCPU.cc:936), so a thread forked in an earlier sub-step of the
    slice neither raises the sub-step gate nor gets scheduled in the same
    slice -- it first runs in the NEXT slice."""
    p = _params(max_threads=2, slicing=1)
    s = _thread_instset()
    fork, inc, dec = s.opcode("fork-th"), s.opcode("inc"), s.opcode("dec")
    nopA = s.opcode("nop-A")
    # 0:fork, 1:inc (child starts here), 2:dec (parent resumes here)
    st = _one_org(p, [fork, inc, dec, nopA, nopA, nopA, nopA, nopA])
    st = _run(p, st, 1)
    # slice 1: only the fork executed.  The child exists but must NOT
    # have run its inc yet (the pre-fix code both gated sub-step 1 open
    # via the recomputed thread count and scheduled the newborn in it).
    assert bool(st.t_alive[0, 0])
    assert int(st.t_heads[0, 0, 0]) == 1           # child parked at fork+1
    assert int(st.t_regs[0, 0, 1]) == 0            # child has not run inc
    assert int(st.regs[0, 0]) == 0                 # parent has not run dec
    assert int(st.time_used[0]) == 1               # one charge per slice
    st = _run(p, st, 1, seed=3)
    # slice 2: both threads run -- child inc (?BX?), parent dec + nop-A
    assert int(st.t_regs[0, 0, 1]) == 1
    assert int(st.regs[0, 0]) == -1
    assert int(st.time_used[0]) == 2


def test_divide_resets_threads():
    """A successful divide collapses the parent to a single thread."""
    cfg = AvidaConfig()
    cfg.WORLD_X = 2
    cfg.WORLD_Y = 2
    cfg.TPU_MAX_MEMORY = 320       # room for the ancestor + h-alloc
    cfg.MAX_CPU_THREADS = 2
    cfg.COPY_MUT_PROB = 0.0
    p = make_world_params(cfg, _thread_instset(),
                          default_logic9_environment())
    from avida_tpu.core.state import init_population
    from avida_tpu.world import default_ancestor
    s = _thread_instset()
    anc = default_ancestor(s)
    st = init_population(p, anc, jax.random.key(0), inject_cell=0)
    # force a fake multi-thread state, then run to the ancestor's divide
    st = st.replace(t_alive=st.t_alive.at[0, 0].set(True))
    mask = jnp.zeros(p.num_cells, bool).at[0].set(True)
    step = jax.jit(lambda s, k: micro_step_threads(
        p, s, k, mask & ~s.divide_pending))
    key = jax.random.key(1)
    for c in range(900):
        key, k = jax.random.split(key)
        st = step(st, k)
        if c % 50 == 49 and bool(st.divide_pending[0]):
            break
    assert bool(st.divide_pending[0]), "ancestor never divided"
    assert not bool(st.t_alive[0, 0])
    assert int(st.cur_thread[0]) == 0


def test_thread_configs_route_off_the_kernel():
    """Thread configs AND thread-instruction sets (even at T=1: fork-th
    still skips an extra IP step) run on the XLA path only."""
    from avida_tpu.ops.pallas_cycles import eligible
    assert not eligible(_params(max_threads=2))
    assert not eligible(_params(max_threads=1))   # instset has fork-th
    cfg = AvidaConfig()
    cfg.WORLD_X = 2
    cfg.WORLD_Y = 2
    plain = make_world_params(cfg, default_instset(),
                              default_logic9_environment())
    assert eligible(plain)
