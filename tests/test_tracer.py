"""Flight recorder + metrics exporter (observability/tracer.py,
observability/exporter.py, ops/update.trace_pre_phase/trace_post_phase).

The contract under test, in order of importance:

 - OFF is free: the default config carries no ring arrays (None fields,
   empty pytrees) -- the jaxpr gate itself is tests/test_jaxpr_snapshot.
 - ON is invisible to evolution: bit-identical trajectories with
   TPU_TRACE=1 vs off, on the XLA path and the lane-packed Pallas path
   (slow tier).
 - Overflow drops the OLDEST events and counts the drops; it never
   forces an early sync.
 - A SIGTERM-preempted run's checkpoint + runlog hold the drained trace
   up to the last chunk boundary, and the resumed run continues
   bit-exactly with the recorder still on (slow tier).
 - metrics.prom / --status reflect a LIVE run within one chunk of real
   time (polled from a second thread while the run owns the device).

Satellite regressions ride along: runlog trim edge cases (torn tail,
strict cutoff, header-only file), the run()-twice .dat truncation wart,
and the scripts/trace_tool.py Chrome-trace round-trip.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

# ring rows are drain scratch past the cursor (zero after any boundary
# drain), exactly like the newborn ring: compare only live rows
_SCRATCH_ROWS = ("nb_genome", "nb_len", "nb_cell", "nb_parent", "nb_update",
                 "tr_update", "tr_cell", "tr_code", "tr_payload")


def _assert_states_equal(sa, sb):
    for name in sa.__dataclass_fields__:
        va, vb = np.asarray(getattr(sa, name)), np.asarray(getattr(sb, name))
        if name in _SCRATCH_ROWS:
            cnt_field = "nb_count" if name.startswith("nb_") else "tr_count"
            cnt = int(np.asarray(getattr(sa, cnt_field)))
            va, vb = va[:cnt], vb[:cnt]
        np.testing.assert_array_equal(va, vb, err_msg=f"field {name}")


def _world(tmpdir, seed=11, trace=0, pallas=False, extra=()):
    from avida_tpu.config import AvidaConfig
    from avida_tpu.world import World

    cfg = AvidaConfig()
    cfg.WORLD_X = 8
    cfg.WORLD_Y = 8
    cfg.TPU_MAX_MEMORY = 256
    cfg.RANDOM_SEED = seed
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    if trace:
        cfg.set("TPU_TRACE", 1)
        cfg.set("TPU_TRACE_CAP", 512)
    if pallas:
        cfg.TPU_USE_PALLAS = 1        # interpret mode on CPU
        cfg.COPY_MUT_PROB = 0.0
        cfg.DIVIDE_INS_PROB = 0.0
        cfg.DIVIDE_DEL_PROB = 0.0
        cfg.SLICING_METHOD = 0
        cfg.set("TPU_SYSTEMATICS", 0)
    for k, v in extra:
        cfg.set(k, v)
    w = World(cfg=cfg, data_dir=str(tmpdir))
    w.events = []
    return w


def _trace_records(data_dir):
    recs = []
    path = os.path.join(str(data_dir), "telemetry.jsonl")
    if not os.path.exists(path):
        return recs
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("record") == "trace":
                recs.append(rec)
    return recs


# ---------------------------------------------------------------- off path

def test_disabled_world_has_no_ring_and_no_trace_output(tmp_path):
    """TPU_TRACE=0 (default): no ring arrays on the state (None fields,
    empty pytrees -- the jaxpr-identity precondition), no tracer, no
    trace records, no metrics.prom."""
    w = _world(tmp_path)
    w.inject()
    w.run(max_updates=3)
    assert w.params.trace_cap == 0
    assert w.state.tr_update is None and w.state.tr_count is None
    assert w.tracer is None and w.exporter is None
    assert _trace_records(tmp_path) == []
    assert not os.path.exists(os.path.join(str(tmp_path), "metrics.prom"))


# ------------------------------------------------------------- ring units

def test_ring_order_overflow_semantics():
    from avida_tpu.observability.tracer import ring_order

    assert ring_order(3, 8).tolist() == [0, 1, 2]
    assert ring_order(8, 8).tolist() == list(range(8))
    # 11 events in a cap-8 ring: survivors are events 3..10 at slots 3..7,0..2
    assert ring_order(11, 8).tolist() == [3, 4, 5, 6, 7, 0, 1, 2]


def test_trace_append_drops_oldest_keeps_cursor():
    """Device-side append: slot i %% cap, monotone cursor, masked lanes
    scattered to the dropped index -- overflow keeps the NEWEST events."""
    import jax.numpy as jnp
    from types import SimpleNamespace

    from avida_tpu.core.state import zeros_population
    from avida_tpu.ops.update import _trace_append

    cap = 4
    params = SimpleNamespace(trace_cap=cap)
    st = zeros_population(6, 8, 2, trace_cap=cap)
    cells = jnp.arange(6, dtype=jnp.int32)
    mask = jnp.asarray([True, False, True, True, True, True])
    st = _trace_append(params, st, mask, cells, 2, cells * 10, jnp.int32(7))
    assert int(st.tr_count) == 5                 # cursor counts ALL events
    # events are cells 0,2,3,4,5; cap 4 keeps the newest four: 2,3,4,5
    # at slots 1,2,3,0 (event numbers 1..4 mod 4)
    assert np.asarray(st.tr_cell).tolist() == [5, 2, 3, 4]
    assert np.asarray(st.tr_payload).tolist() == [50, 20, 30, 40]
    assert np.asarray(st.tr_update).tolist() == [7] * 4
    assert np.asarray(st.tr_code).tolist() == [2] * 4


def test_drain_reports_drop_count(tmp_path):
    """FlightRecorder.drain on an overflowed snapshot: newest events
    land per-update in the runlog, the window's first record carries
    the drop count, totals accumulate."""
    from types import SimpleNamespace

    from avida_tpu.observability.tracer import EV_BIRTH, FlightRecorder

    stub = SimpleNamespace(telemetry=None, _dat_append=False,
                           data_dir=str(tmp_path))
    rec = FlightRecorder(stub)
    cap, count = 8, 13                 # 5 dropped (events 0..4)
    ev = np.arange(count, dtype=np.int32)
    kept = ev[count - cap:]
    ring = np.zeros(cap, np.int32)
    for i in kept:
        ring[i % cap] = i
    rec.drain({"tr_update": ring // 6, "tr_cell": ring,
               "tr_code": np.full(cap, EV_BIRTH, np.int32),
               "tr_payload": ring, "tr_count": np.int32(count),
               "update_at": 3, "host_events": []})
    rec.close()
    assert rec.dropped_total == 5
    assert rec.events_total == cap
    recs = _trace_records(tmp_path)
    assert recs[0]["dropped"] == 5
    assert all("dropped" not in r for r in recs[1:])
    # chronological within the window, grouped per update
    drained = [e[0] for r in recs for e in r["events"]]
    assert sorted(drained) == kept.tolist()
    assert [r["update"] for r in recs] == sorted({int(u) for u in kept // 6})


@pytest.mark.slow
def test_ring_overflow_in_live_run(tmp_path):
    """A cap-4 ring under a guaranteed one-event-per-update load (stall
    threshold > 1 always fires) overflows inside chunked stretches:
    drops are counted, never synced early, and the run is unperturbed."""
    w = _world(tmp_path / "t", trace=1,
               extra=[("TPU_TRACE_CAP", 4), ("TPU_TRACE_STALL_UTIL", 1.1)])
    w.inject()
    w.run(max_updates=24)
    assert w.params.trace_cap == 4
    assert w.tracer.events_total + w.tracer.dropped_total >= 24
    recs = _trace_records(tmp_path / "t")
    assert sum(r.get("dropped", 0) for r in recs) == w.tracer.dropped_total

    # same run, big ring: identical trajectory (drops are accounting,
    # not behavior)
    w2 = _world(tmp_path / "big", trace=1,
                extra=[("TPU_TRACE_STALL_UTIL", 1.1)])
    w2.inject()
    w2.run(max_updates=24)
    assert w2.tracer.dropped_total == 0
    _assert_states_equal(w.state, w2.state)


# ------------------------------------------------------------ bit-exactness

@pytest.mark.slow
def test_trace_bit_exact_xla(tmp_path):
    """TPU_TRACE=1 leaves the evolved trajectory bit-identical on the
    XLA path, and every update up to the final boundary has its trace
    record drained to the runlog (stall threshold 1.1 guarantees at
    least one event per update)."""
    wa = _world(tmp_path / "off", seed=23)
    wa.inject()
    wa.run(max_updates=20)

    wb = _world(tmp_path / "on", seed=23, trace=1,
                extra=[("TPU_TRACE_STALL_UTIL", 1.1)])
    wb.inject()
    wb.run(max_updates=20)
    assert wb.tracer.events_total >= 20

    for name in wa.state.__dataclass_fields__:
        if name.startswith("tr_"):
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(wa.state, name)),
            np.asarray(getattr(wb.state, name)), err_msg=f"field {name}")
    assert {r["update"] for r in _trace_records(tmp_path / "on")} \
        == set(range(20))


@pytest.mark.slow
def test_trace_bit_exact_pallas_lane_packed(tmp_path):
    """Same guarantee through the Pallas kernel path with lane packing
    active (the ring is WORLD_LEVEL: excluded from the lane permutation
    and the move gather)."""
    from avida_tpu.ops.update import use_pallas_path

    # pin the budget-sort lane-packed path (packed residency would
    # supersede the permutation; it has its own test below)
    lp = [("TPU_PACKED_CHUNK", 0)]
    wa = _world(tmp_path / "off", seed=31, pallas=True, extra=lp)
    assert use_pallas_path(wa.params) and wa.params.lane_perm_k == 1
    wa.inject()
    wa.run(max_updates=12)

    wb = _world(tmp_path / "on", seed=31, trace=1, pallas=True, extra=lp)
    wb.inject()
    wb.run(max_updates=12)

    for name in wa.state.__dataclass_fields__:
        if name.startswith("tr_"):
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(wa.state, name)),
            np.asarray(getattr(wb.state, name)), err_msg=f"field {name}")


@pytest.mark.slow
def test_trace_bit_exact_packed_chunk(tmp_path):
    """Flight recorder under PACKED RESIDENCY (ops/packed_chunk.py,
    mutations ON): trace-on vs trace-off trajectories stay bit-identical,
    and every update's events reach the runlog through the chunk-boundary
    drain -- which reads the ring off CANONICAL state, strictly after
    update_scan's unpack."""
    from avida_tpu.ops import packed_chunk

    extra = [("COPY_MUT_PROB", 0.0075), ("DIVIDE_INS_PROB", 0.05),
             ("DIVIDE_DEL_PROB", 0.05), ("SLICING_METHOD", 1),
             ("TPU_TRACE_STALL_UTIL", 1.1)]
    wa = _world(tmp_path / "off", seed=29, pallas=True, extra=extra)
    wa.inject()
    assert packed_chunk.active(wa.params, wa.state)
    wa.run(max_updates=12)

    wb = _world(tmp_path / "on", seed=29, trace=1, pallas=True, extra=extra)
    wb.inject()
    wb.run(max_updates=12)

    # stall-util 1.1 guarantees at least one event per update: the drain
    # saw every update of every packed chunk
    assert {r["update"] for r in _trace_records(tmp_path / "on")} \
        == set(range(12))
    for name in wa.state.__dataclass_fields__:
        if name.startswith("tr_"):
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(wa.state, name)),
            np.asarray(getattr(wb.state, name)), err_msg=f"field {name}")


@pytest.mark.slow
def test_sigterm_preempt_keeps_drained_trace(tmp_path):
    """SIGTERM mid-run with the recorder on: the final checkpoint and
    the runlog contain the drained trace up to the last chunk boundary
    (one stall event per update guaranteed), the checkpoint serializes
    the ring DRAINED (cursor 0), and a fresh world resumes + finishes
    bit-exactly with the recorder still on."""
    from avida_tpu.config.events import parse_event_line
    from avida_tpu.utils import checkpoint as ckpt_mod

    trace_extra = [("TPU_TRACE_STALL_UTIL", 1.1)]
    wa = _world(tmp_path / "a", trace=1, extra=trace_extra)
    wa.inject()
    wa.run(max_updates=20)

    ckdir = tmp_path / "ck"
    wb = _world(tmp_path / "b", trace=1,
                extra=trace_extra + [("TPU_CKPT_DIR", str(ckdir))])
    wb._action_SendTerm = lambda args: os.kill(os.getpid(), signal.SIGTERM)
    wb.events = [parse_event_line("u 9 SendTerm")]
    wb.inject()
    wb.run(max_updates=20)
    assert wb.preempted and wb.update < 20

    # every update that ran is in the runlog -- nothing lost past the
    # last boundary, nothing invented beyond it
    assert {r["update"] for r in _trace_records(tmp_path / "b")} \
        == set(range(wb.update))

    # the checkpoint's ring is drained: cursor 0, host counters carried
    gens = ckpt_mod.list_generations(str(ckdir))
    manifest, arrays, _ = ckpt_mod.read_generation(gens[-1])
    assert int(arrays["state.tr_count"]) == 0
    host = manifest["host"]
    assert host["tracer"]["events_total"] == wb.tracer.events_total
    assert host["tracer"]["events_total"] >= wb.update

    wc = _world(tmp_path / "c", trace=1,
                extra=trace_extra + [("TPU_CKPT_DIR", str(ckdir))])
    assert wc.resume() == wb.update
    wc.run(max_updates=20)
    _assert_states_equal(wa.state, wc.state)
    # runlog continuity across the preempt/resume: updates 0..19, each
    # exactly once (b owns 0..update-1, the resumed c re-emits from
    # update on)
    seen = sorted(r["update"] for r in
                  _trace_records(tmp_path / "b")
                  + _trace_records(tmp_path / "c"))
    assert seen == list(range(20))


# ------------------------------------------------------- metrics exporter

def test_metrics_prom_written_and_parsed(tmp_path):
    """TPU_METRICS=1 alone (no tracer) publishes the heartbeat; values
    round-trip through the parser and the --status formatter."""
    from avida_tpu.observability.exporter import (METRICS_FILE,
                                                  format_status,
                                                  read_metrics, status_main)

    w = _world(tmp_path, extra=[("TPU_METRICS", 1)])
    w.inject()
    w.run(max_updates=6)
    assert w.tracer is None
    path = os.path.join(str(tmp_path), METRICS_FILE)
    m = read_metrics(path)
    assert m["avida_update"] == 6
    assert m["avida_organisms"] >= 1
    assert m["avida_heartbeat_timestamp_seconds"] <= time.time()
    out = format_status(m)
    assert "update      6" in out
    assert status_main(str(tmp_path)) == 0
    assert status_main(str(tmp_path / "nonexistent")) == 1


def test_metrics_live_polling_between_chunks(tmp_path):
    """The acceptance check: metrics.prom reflects a LIVE run within one
    chunk of real time.  The run owns the main thread; a poller thread
    watches the file and must observe an intermediate update count
    strictly between 0 and the final one (i.e. the heartbeat is
    published at chunk boundaries, not only at exit)."""
    from avida_tpu.observability.exporter import METRICS_FILE, read_metrics

    # chunked run (no telemetry): stretches of up to 8 updates between
    # boundaries, heartbeat republished at each boundary (stall_util 1.1
    # matches the run()-twice test so the two share one compiled program)
    w = _world(tmp_path, trace=1, extra=[("TPU_TRACE_STALL_UTIL", 1.1)])
    path = os.path.join(str(tmp_path), METRICS_FILE)
    seen, stop = set(), threading.Event()

    def poll():
        while not stop.is_set():
            if os.path.exists(path):
                try:
                    seen.add(int(read_metrics(path)["avida_update"]))
                except (KeyError, ValueError, OSError):
                    pass                       # mid-replace race: retry
            time.sleep(0.002)

    t = threading.Thread(target=poll, daemon=True)
    t.start()
    try:
        w.inject()
        w.run(max_updates=40)
    finally:
        stop.set()
        t.join(timeout=10)
    final = int(read_metrics(path)["avida_update"])
    assert final == 40
    live = {u for u in seen if 0 < u < 40}
    assert live, f"poller saw no intermediate heartbeat (seen={seen})"


# ------------------------------------------------- runlog trim satellites

def _write_runlog(path, lines):
    with open(path, "w") as f:
        for rec in lines:
            f.write((rec if isinstance(rec, str) else json.dumps(rec))
                    + "\n")


def test_trim_drops_torn_tail(tmp_path):
    """A partial JSON line (crash mid-write) is dropped by the trim."""
    from avida_tpu.observability.runlog import trim_update_records

    path = str(tmp_path / "telemetry.jsonl")
    _write_runlog(path, [{"record": "meta", "seed": 1},
                         {"record": "update", "update": 0},
                         {"record": "trace", "update": 0, "events": []}])
    with open(path, "a") as f:
        f.write('{"record": "update", "upd')      # torn tail, no newline
    trim_update_records(path, 5)
    recs = [json.loads(x) for x in open(path)]
    assert [r["record"] for r in recs] == ["meta", "update", "trace"]


def test_trim_strict_cutoff_reemits_restored_update(tmp_path):
    """A checkpoint at update N owns records 0..N-1: trim drops update
    AND trace records >= N (the resumed run re-emits its own), keeps
    meta/event records regardless."""
    from avida_tpu.observability.runlog import trim_update_records

    path = str(tmp_path / "telemetry.jsonl")
    _write_runlog(path, [{"record": "meta"},
                         {"record": "update", "update": 3},
                         {"record": "trace", "update": 3, "events": [[0, 1, 0]]},
                         {"record": "event", "event": "checkpoint_saved"},
                         {"record": "update", "update": 4},
                         {"record": "trace", "update": 4, "events": []}])
    trim_update_records(path, 4)
    recs = [json.loads(x) for x in open(path)]
    assert [r.get("update") for r in recs] == [None, 3, 3, None]
    assert recs[3]["record"] == "event"


def test_trim_header_only_file(tmp_path):
    """Only the meta header: trim is a no-op that keeps the file intact
    (and a missing file stays a no-op)."""
    from avida_tpu.observability.runlog import trim_update_records

    path = str(tmp_path / "telemetry.jsonl")
    _write_runlog(path, [{"record": "meta", "seed": 9}])
    before = open(path).read()
    trim_update_records(path, 0)
    assert open(path).read() == before
    trim_update_records(str(tmp_path / "absent.jsonl"), 0)   # no raise


# ------------------------------------------------- run()-twice satellite

def test_run_twice_appends_dat_files(tmp_path):
    """The PR-4 wart: a second run() on the same World must EXTEND its
    own .dat files (single header, continuous rows), not truncate them.
    Also covers the trace runlog: records from both segments survive."""
    from avida_tpu.config.events import parse_event_line

    w = _world(tmp_path, trace=1, extra=[("TPU_TRACE_STALL_UTIL", 1.1)])
    w.events = [parse_event_line("u 0:2:end PrintAverageData average.dat")]
    w.inject()
    w.run(max_updates=6)
    w.run(max_updates=12)

    lines = open(os.path.join(str(tmp_path), "average.dat")).readlines()
    rows = [ln for ln in lines if ln.strip() and not ln.startswith("#")]
    updates = [int(float(r.split()[0])) for r in rows]
    assert updates == list(range(0, 12, 2))    # continuous, no restart at 6
    # single header block: the second run() appended instead of rewriting
    assert sum(1 for ln in lines if ln.startswith("#  1:")) == 1

    assert {r["update"] for r in _trace_records(tmp_path)} == set(range(12))


# ------------------------------------------------------ trace_tool round-trip

def test_trace_tool_chrome_roundtrip(tmp_path):
    """to-chrome followed by from-chrome reproduces the per-update event
    lists exactly; phase records become duration events."""
    import trace_tool

    path = str(tmp_path / "telemetry.jsonl")
    _write_runlog(path, [
        {"record": "meta", "seed": 5, "platform": "cpu"},
        {"record": "update", "update": 0, "wall_ms": 2.0,
         "phases": {"schedule": 0.5, "while_loop": 1.0}, "counters": {}},
        {"record": "trace", "update": 0,
         "events": [[3, 1, 7], [-1, 4, 9000]], "dropped": 4},
        {"record": "trace", "update": 2, "events": [[5, 2, 11]]},
    ])
    doc = trace_tool.to_chrome(path)
    kinds = {e["ph"] for e in doc["traceEvents"]}
    assert {"X", "i", "M"} <= kinds
    insts = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert len(insts) == 4            # 3 events + 1 trace_dropped marker

    # each phase gets its own named row; phase brackets land on it
    names = {e["args"]["name"]: e["tid"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    for phase in ("schedule", "while_loop"):
        row = names[f"phase:{phase}"]
        assert any(e["ph"] == "X" and e["name"] == phase
                   and e["tid"] == row for e in doc["traceEvents"])

    out = str(tmp_path / "trace.json")
    with open(out, "w") as f:
        json.dump(doc, f)
    recs = trace_tool.from_chrome(out)
    assert recs == [
        {"record": "trace", "update": 0,
         "events": [[3, 1, 7], [-1, 4, 9000]], "dropped": 4},
        {"record": "trace", "update": 2, "events": [[5, 2, 11]]},
    ]

    s = trace_tool.summary(path)
    assert "events total:               3" in s
