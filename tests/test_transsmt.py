"""TransSMT hardware + host-parasite coevolution.

Covers BASELINE.json config 4 (transsmt + parasites).  Reference:
cHardwareTransSMT (cpu/cHardwareTransSMT.cc) -- stack-based CPU with
memory spaces; Inst_Inject (cc:1657) parasite transmission; virulence
thread scheduling (cc:218-248); scenario modeled on the reference
default_transsmt_100u and parasite tests.
"""

from __future__ import annotations

import numpy as np

from avida_tpu.config import AvidaConfig, transsmt_instset
from avida_tpu.config.events import parse_event_line
from avida_tpu.world import World, default_ancestor

import pytest  # noqa: E402

pytestmark = pytest.mark.slow


def _world(**kw):
    cfg = AvidaConfig()
    cfg.WORLD_X = 10
    cfg.WORLD_Y = 10
    cfg.TPU_MAX_MEMORY = 160
    cfg.RANDOM_SEED = 31
    cfg.INST_SET = "transsmt"
    cfg.AVE_TIME_SLICE = 100
    cfg.TPU_MAX_STEPS_PER_UPDATE = 100
    cfg.COPY_MUT_PROB = 0.0       # deterministic replication for the test
    cfg.DIVIDE_INS_PROB = 0.0
    cfg.DIVIDE_DEL_PROB = 0.0
    cfg.set("TPU_SYSTEMATICS", 0)
    for k, v in kw.items():
        cfg.set(k, v)
    return World(cfg=cfg)


def test_transsmt_instset_loads():
    s = transsmt_instset()
    assert s.hw_type == 2
    assert "Inject" in s.inst_names and "Divide" in s.inst_names
    w = _world()
    assert w.params.hw_type == 2
    anc = default_ancestor(w.instset)
    assert len(anc) == 100


def test_transsmt_ancestor_self_replicates():
    """The stock transsmt ancestor copies itself through its write buffer
    and divides: population must grow (reference default_transsmt_100u)."""
    w = _world()
    w.inject()
    w.run(max_updates=40)
    n = w.num_organisms
    assert n > 1, f"transsmt ancestor never divided (organisms={n})"
    # offspring genomes are transsmt programs of plausible length
    st = w.state
    alive = np.asarray(st.alive)
    lens = np.asarray(st.genome_len)[alive]
    assert (lens >= 50).all() and (lens <= 160).all(), lens


def test_host_parasite_world_both_persist():
    """Inject the stock parasite into a host world: parasites must spread
    (Inst_Inject through neighbors) while hosts keep reproducing --
    BASELINE config 4's 'both populations persisting'."""
    w = _world(PARASITE_VIRULENCE=0.8)
    w.events = [parse_event_line("u begin Inject"),
                parse_event_line("u 12 InjectAll"),
                parse_event_line("u 20 InjectParasite - - 0 30")]
    w.inject()
    # fill a block of cells so parasites have hosts to spread into
    for c in range(0, 30):
        w.inject(cell=c)
    w._action_InjectParasite(["-", "-", "0", "10"])
    assert int(np.asarray(w.state.parasite_active).sum()) == 10
    w.run(max_updates=40)
    st = w.state
    hosts = int(np.asarray(st.alive).sum())
    parasites = int(np.asarray(st.parasite_active & st.alive).sum())
    assert hosts > 10, f"host population collapsed: {hosts}"
    assert parasites > 0, "parasites went extinct immediately"
    # transmission happened: infections beyond the initially seeded cells
    infected_cells = np.nonzero(np.asarray(st.parasite_active))[0]
    assert (infected_cells >= 10).any() or parasites >= 10, infected_cells
