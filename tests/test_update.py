"""Full update-loop tests: scheduler + lockstep stepping + birth engine.

Covers SURVEY.md §7 steps 3-6 behavior: population growth from a single
ancestor, determinism (same seed => identical state), and task rewards
feeding merit.
"""

import jax
import jax.numpy as jnp
import numpy as np

from avida_tpu.config import AvidaConfig, default_instset
from avida_tpu.config.environment import default_logic9_environment
from avida_tpu.core.state import init_population, make_world_params
from avida_tpu.ops import birth as birth_ops
from avida_tpu.ops.update import update_step, summarize
from avida_tpu.world import World, default_ancestor


def make_world(nx=10, ny=10, seed=11, **cfg_kw):
    cfg = AvidaConfig()
    cfg.WORLD_X = nx
    cfg.WORLD_Y = ny
    cfg.TPU_MAX_MEMORY = 320
    cfg.RANDOM_SEED = seed
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    iset = default_instset()
    env = default_logic9_environment()
    params = make_world_params(cfg, iset, env)
    genome = default_ancestor(iset)
    st = init_population(params, genome, jax.random.key(seed))
    nbrs = jnp.asarray(birth_ops.neighbor_table(nx, ny, cfg.WORLD_GEOMETRY))
    return params, st, nbrs


def run_updates(params, st, nbrs, n_updates, seed=3):
    key = jax.random.key(seed)
    for u in range(n_updates):
        key, k = jax.random.split(key)
        st, _ = update_step(params, st, k, nbrs, jnp.int32(u))
    return st


def test_population_grows():
    params, st, nbrs = make_world()
    # gestation 389 cycles at ~30/update => first birth by update ~14
    st = run_updates(params, st, nbrs, 16)
    n = int(st.alive.sum())
    assert n >= 2, f"expected first birth by update 16, got {n} organisms"
    st = run_updates(params, st, nbrs, 50, seed=4)
    n2 = int(st.alive.sum())
    assert n2 > 4, f"population should keep growing, got {n2}"
    # offspring carry sensible state
    alive = np.asarray(st.alive)
    assert (np.asarray(st.genome_len)[alive] > 50).all()
    assert (np.asarray(st.merit)[alive] > 0).all()


def test_determinism_same_seed():
    params, st1, nbrs = make_world(seed=5)
    params2, st2, _ = make_world(seed=5)
    a = run_updates(params, st1, nbrs, 25, seed=9)
    b = run_updates(params2, st2, nbrs, 25, seed=9)
    for name in ("mem", "alive", "merit", "heads", "regs", "time_used"):
        np.testing.assert_array_equal(np.asarray(getattr(a, name)),
                                      np.asarray(getattr(b, name)),
                                      err_msg=f"field {name} diverged")


def test_neighbor_table_torus():
    t = birth_ops.neighbor_table(5, 4, 2)
    assert t.shape == (20, 8)
    # cell 0 (x=0,y=0) neighbors wrap
    assert set(t[0]) == {19, 15, 16, 4, 1, 9, 5, 6}
    # every cell has 8 distinct neighbors on a torus >= 3x3
    t2 = birth_ops.neighbor_table(3, 3, 2)
    for c in range(9):
        assert len(set(t2[c])) == 8


def test_constant_slicing_grows_too():
    params, st, nbrs = make_world(SLICING_METHOD=0)
    st = run_updates(params, st, nbrs, 16)
    assert int(st.alive.sum()) >= 2


def test_summarize_fields():
    params, st, nbrs = make_world()
    st = run_updates(params, st, nbrs, 20)
    s = summarize(params, st)
    assert int(s["num_organisms"]) == int(st.alive.sum())
    assert float(s["ave_merit"]) > 0
    assert s["task_counts"].shape == (9,)


def test_total_insts_words_exact_without_x64():
    """summarize's lifetime executed total must not silently wrap when
    x64 is off: the three 11-bit field sums recombine exactly on the
    host (total here ~2.1e11, far beyond int32), and the scalar f32
    fallback is positive/monotone rather than wrapped-negative."""
    from avida_tpu.ops.update import total_insts_exact
    params, st, nbrs = make_world()
    n = st.insts_executed.shape[0]
    per_cell = 2**31 - 5
    st = st.replace(insts_executed=jnp.full(n, per_cell, jnp.int32))
    s = summarize(params, st)
    assert not jax.config.jax_enable_x64
    assert total_insts_exact(s["total_insts_words"]) == n * per_cell
    approx = float(np.asarray(s["total_insts"]))
    assert approx > 0
    assert abs(approx - n * per_cell) / (n * per_cell) < 1e-6


def test_world_end_to_end(tmp_path):
    w = World(overrides=[("WORLD_X", 8), ("WORLD_Y", 8), ("RANDOM_SEED", 3),
                         ("TPU_MAX_MEMORY", 320)],
              data_dir=str(tmp_path / "data"))
    w.run(max_updates=20)
    assert w.num_organisms >= 2
    avg = (tmp_path / "data" / "average.dat").read_text()
    assert avg.startswith("# Avida Average Data")
    rows = [l for l in avg.splitlines() if l and not l.startswith("#")]
    assert len(rows) >= 1
